#!/usr/bin/env python
"""In-container workload example: report metrics through the TaskBridge.

The worker injects SOCKET_PATH / PRIME_TASK_ID / NODE_ADDRESS into every
task's environment (protocol_tpu/services/worker.py, mirroring the
reference's examples/python/taskbridge_basic.py client of the docker
taskbridge socket). A workload connects to the unix socket and writes
concatenated JSON objects:

    {"task_id": "...", "loss": 0.25, "throughput": 1234.0}

Those land in the worker's metric store and flow to the orchestrator on the
next heartbeat.
"""

import json
import os
import socket
import time

SOCKET_PATH = os.environ.get("SOCKET_PATH", "/tmp/protocol_tpu_worker_0/bridge.sock")
TASK_ID = os.environ.get("PRIME_TASK_ID", "example-task")


def main() -> None:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(SOCKET_PATH)
    try:
        for step in range(5):
            metrics = {
                "task_id": TASK_ID,
                "loss": 1.0 / (step + 1),
                "step": float(step),
            }
            sock.sendall(json.dumps(metrics).encode())
            print(f"sent metrics: {metrics}")
            time.sleep(1.0)
    finally:
        sock.close()


if __name__ == "__main__":
    main()
