#!/usr/bin/env python
"""In-container workload example: submit validatable work output.

Equivalent of the reference's examples/python/work_validation.py: after
producing an artifact, the workload reports its sha256 and claimed FLOPs
through the TaskBridge. The worker requests a signed upload URL from the
orchestrator, submits the work key on the ledger, and the validator later
verifies it through the toploc-style pipeline (accepting, rejecting with a
stake slash, or soft-invalidating on a work-unit mismatch).

File names matching ``...-<groupid>-<size>-<filenum>-<idx>.<ext>`` are
validated as a group once all members arrive.
"""

import hashlib
import json
import os
import socket

SOCKET_PATH = os.environ.get("SOCKET_PATH", "/tmp/protocol_tpu_worker_0/bridge.sock")
TASK_ID = os.environ.get("PRIME_TASK_ID", "example-task")


def main() -> None:
    # produce an artifact
    payload = os.urandom(1024)
    sha = hashlib.sha256(payload).hexdigest()
    file_name = f"synthetic-{sha[:8]}-1-0-0.parquet"
    out_path = f"/tmp/{file_name}"
    with open(out_path, "wb") as f:
        f.write(payload)

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(SOCKET_PATH)
    try:
        message = {
            "output": {
                "sha256": sha,
                "output_flops": 123456,
                "file_name": file_name,
                "save_path": out_path,
            }
        }
        sock.sendall(json.dumps(message).encode())
        print(f"submitted work: sha={sha[:16]}... flops=123456")
    finally:
        sock.close()


if __name__ == "__main__":
    main()
