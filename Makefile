# Developer entry points (the reference drives its dev environment from a
# Makefile too: reth devnet + redis + tmux service panes; here the whole
# cluster is one process).

PY ?= python

.PHONY: test test-fast native devnet devnet-persistent bench bench-scaling clean lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x -m "not slow"

# native CPU assignment engine (ctypes-loaded shared library; -pthread
# for the multi-threaded engine=native-mt variants)
native:
	g++ -O3 -march=native -std=gnu++17 -pthread -shared -fPIC -o native/libassign_engine.so native/assign_engine.cpp

# one-command local cluster: ledger API + discovery + orchestrator +
# validator + workers. See python -m protocol_tpu.devnet --help.
devnet:
	$(PY) -m protocol_tpu.devnet --workers 2 --cpu

# persistent devnet: docker runtime + remote scheduler seam + AOF/ledger
# state surviving restarts
devnet-persistent:
	$(PY) -m protocol_tpu.devnet --workers 2 --cpu --runtime docker \
	  --scheduler-backend remote --state-dir /var/tmp/protocol_tpu_devnet

# the scheduler-kernel benchmark (real accelerator; prints one JSON line)
bench:
	$(PY) bench.py

# ladder-#4 scaling measurement (per-shard rates + HBM envelopes; see
# SCALING.md). Runs on the chip when healthy, CPU mesh otherwise.
bench-scaling:
	$(PY) bench_scaling.py --full

# full-scale matcher tests (100k nodes x 10k slots; ~4 min on CPU)
scale-tests:
	PROTOCOL_TPU_SCALE_TESTS=1 $(PY) -m pytest tests/test_scale_matcher.py -v

# regenerate protobuf messages for the gRPC shim
lint:
	python scripts/lint.py

proto:
	protoc --python_out=. protocol_tpu/proto/scheduler.proto

clean:
	rm -rf native/libassign_engine.so **/__pycache__ .pytest_cache
