# Developer entry points (the reference drives its dev environment from a
# Makefile too: reth devnet + redis + tmux service panes; here the whole
# cluster is one process).

PY ?= python

.PHONY: test test-fast native devnet bench clean lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x -m "not slow"

# native CPU assignment engine (ctypes-loaded shared library)
native:
	g++ -O3 -march=native -shared -fPIC -o native/libassign_engine.so native/assign_engine.cpp

# one-command local cluster: ledger API + discovery + orchestrator +
# validator + workers. See python -m protocol_tpu.devnet --help.
devnet:
	$(PY) -m protocol_tpu.devnet --workers 2 --cpu

# the scheduler-kernel benchmark (real accelerator; prints one JSON line)
bench:
	$(PY) bench.py

# regenerate protobuf messages for the gRPC shim
proto:
	protoc --python_out=. protocol_tpu/proto/scheduler.proto

clean:
	rm -rf native/libassign_engine.so **/__pycache__ .pytest_cache
