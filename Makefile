# Developer entry points (the reference drives its dev environment from a
# Makefile too: reth devnet + redis + tmux service panes; here the whole
# cluster is one process).

PY ?= python

# Native engine codegen flags. -march=x86-64-v2 (not -march=native): the
# .so must load on any CI/prod host, and sanitizer stacks want a stable
# ISA — the AVX2/AVX-512 kernels are compiled in via per-function target
# attributes and selected at RUNTIME, so one baseline .so carries every
# ISA. -ffp-contract=off: no silent a*b+c fusion — every fma is explicit,
# one float pipeline per ISA (the determinism contract). Override for
# tuned local builds: make native NATIVE_CFLAGS="-O3 -march=native -ffp-contract=off"
# (protocol_tpu/native/__init__.py honors the same env var).
NATIVE_CFLAGS ?= -O3 -march=x86-64-v2 -ffp-contract=off
NATIVE_BASE = -std=gnu++17 -pthread -shared -fPIC
# sanitizer builds: -O1 -g keeps symbols/line numbers in reports and the
# slowdown usable; separate .so names so they never clobber the prod build
NATIVE_SAN_CFLAGS ?= -O1 -g -march=x86-64-v2 -ffp-contract=off

.PHONY: test test-fast native native-tsan native-asan native-avx2 native-avx512 sanitize devnet devnet-persistent bench bench-scaling clean lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x -m "not slow"

# native CPU assignment engine (ctypes-loaded shared library; -pthread
# for the multi-threaded engine=native-mt variants)
native:
	g++ $(NATIVE_CFLAGS) $(NATIVE_BASE) -o native/libassign_engine.so native/assign_engine.cpp

# sanitizer-instrumented variants (selected at runtime via
# PROTOCOL_TPU_NATIVE_SANITIZE=tsan|asan; driven end-to-end by
# scripts/sanitize_native.py, which LD_PRELOADs the matching runtime)
native-tsan:
	g++ $(NATIVE_SAN_CFLAGS) -fsanitize=thread $(NATIVE_BASE) -o native/libassign_engine.tsan.so native/assign_engine.cpp

native-asan:
	g++ $(NATIVE_SAN_CFLAGS) -fsanitize=address,undefined -fno-sanitize-recover=all $(NATIVE_BASE) -o native/libassign_engine.asan.so native/assign_engine.cpp

# ISA-default variants (selected at runtime via
# PROTOCOL_TPU_NATIVE_ISA_VARIANT=avx2|avx512): identical codegen — every
# .so carries all per-ISA kernels — but the baked DEFAULT dispatch differs,
# for hosts where no env plumbing reaches the process. The runtime clamp
# still falls back to what the CPU supports. PROTOCOL_TPU_NATIVE_ISA
# overrides the baked default in any variant.
native-avx2:
	g++ $(NATIVE_CFLAGS) -DENGINE_DEFAULT_ISA=1 $(NATIVE_BASE) -o native/libassign_engine.avx2.so native/assign_engine.cpp

native-avx512:
	g++ $(NATIVE_CFLAGS) -DENGINE_DEFAULT_ISA=2 $(NATIVE_BASE) -o native/libassign_engine.avx512.so native/assign_engine.cpp

# TSan stress gate over all three -mt kernels (threads 1/2/4/8, churned
# warm-arena re-solves); add --sanitizer asan for the memory/UB pass
sanitize:
	$(PY) scripts/sanitize_native.py --sanitizer tsan

# one-command local cluster: ledger API + discovery + orchestrator +
# validator + workers. See python -m protocol_tpu.devnet --help.
devnet:
	$(PY) -m protocol_tpu.devnet --workers 2 --cpu

# persistent devnet: docker runtime + remote scheduler seam + AOF/ledger
# state surviving restarts
devnet-persistent:
	$(PY) -m protocol_tpu.devnet --workers 2 --cpu --runtime docker \
	  --scheduler-backend remote --state-dir /var/tmp/protocol_tpu_devnet

# the scheduler-kernel benchmark (real accelerator; prints one JSON line)
bench:
	$(PY) bench.py

# ladder-#4 scaling measurement (per-shard rates + HBM envelopes; see
# SCALING.md). Runs on the chip when healthy, CPU mesh otherwise.
bench-scaling:
	$(PY) bench_scaling.py --full

# full-scale matcher tests (100k nodes x 10k slots; ~4 min on CPU)
scale-tests:
	PROTOCOL_TPU_SCALE_TESTS=1 $(PY) -m pytest tests/test_scale_matcher.py -v

# fail-the-build lint discipline: the hermetic unused-import gate, the
# project rule engine (determinism / lock / dtype / dense-alloc
# contracts — scripts/lints/), and the whole-program analyzer
# (lock-order / protocol-sm / jax-purity / jax-retrace / spmd-contract
# — scripts/analysis/)
lint:
	$(PY) scripts/lint.py
	$(PY) -m scripts.lints
	$(PY) -m scripts.analysis

proto:
	protoc --python_out=. protocol_tpu/proto/scheduler.proto

clean:
	rm -rf native/libassign_engine.so native/libassign_engine.tsan.so \
	  native/libassign_engine.asan.so native/libassign_engine.avx2.so \
	  native/libassign_engine.avx512.so **/__pycache__ .pytest_cache
