"""Deterministic fan-out planning for fleet-level stream events.

The core invention is the **sentinel seq tier**: a fleet-level event
(mass blackout, ejection storm) is decomposed into per-source leave
events whose seq sits ABOVE every seq a workload source will ever emit
(workload seqs are per-source event counters — thousands; the tiers
start at 2^30). Under the stream engine's per-source latest-wins
supersession that makes the converged columns independent of WHERE the
fan-out interleaves each session's firehose:

  * a workload heartbeat from a stormed source arriving AFTER the storm
    leave carries a lower seq -> superseded -> dropped;
  * the storm leave arriving late (chaos'd delivery) still wins over
    every earlier workload event for that source;
  * two storms hitting the same source are ordered by tier + index
    (mass index / topology generation, both monotone).

So the final reconciled plan of a chaos'd, storm-injected fleet session
is bit-identical to a fault-free replay of the same event multiset —
the phase-A gate of ``perf_gate.py --dstream`` asserts exactly that.

Everything here is a pure function of its arguments (sha1 hashing for
storm membership, the ring for homing): no clocks, no RNG state.
"""

from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np

from protocol_tpu.stream.events import StreamEvent

# seq tiers (workload seqs are per-source event counters, << 2^29)
PAD_SEQ_BASE = 1 << 29     # cadence-advancing no-op pads
MASS_SEQ_BASE = 1 << 30    # + mass event index
STORM_SEQ_BASE = (1 << 30) + (1 << 20)  # + topology generation
PAD_SOURCE = "~pad"        # never minted by the synth factory


def _h(key: str) -> int:
    return int.from_bytes(
        hashlib.sha1(key.encode()).digest()[:8], "big"
    )


def source_home(topology, session_id: str, source: str) -> str:
    """The proc id an event source is homed on: ring-routed by the
    (session, source) pair, so homes are deterministic given the
    topology and spread independently of the sessions' own homes (a
    provider node connects to SOME process; which one is ring luck)."""
    ep = topology.endpoint_for(f"{session_id}/{source}")
    return topology.procs.get(ep, ep)


def affected_rows(
    topology, session_id: str, dead_proc_id: str, n_providers: int
) -> np.ndarray:
    """Provider rows whose event source was homed on ``dead_proc_id``
    — the membership of that process's ejection storm for one session.
    Pure in (topology, session, proc): every driver computes the same
    set, and a replay recomputes it bit-for-bit."""
    rows = [
        r for r in range(int(n_providers))
        if source_home(topology, session_id, f"p{r}") == dead_proc_id
    ]
    return np.asarray(rows, np.int32)


def storm_rows(
    seed: int, tag: str, n_rows: int, frac: float
) -> np.ndarray:
    """Seeded deterministic subset of rows a mass event takes down —
    sha1-ranked choice (the faults/plan idiom), no RNG state. At least
    one row for any frac > 0 so an armed storm is never a no-op."""
    n_rows = int(n_rows)
    k = min(n_rows, max(1, int(round(n_rows * float(frac)))))
    ranked = sorted(
        range(n_rows), key=lambda r: _h(f"storm/{seed}/{tag}/{r}")
    )
    return np.asarray(sorted(ranked[:k]), np.int32)


def leave_events(
    rows, seq: int, p_cols: dict, kind: str = "leave"
) -> list:
    """Mint one per-source leave event per row at sentinel ``seq``.

    The carried column payload is the SNAPSHOT state of the row with
    ``valid`` forced False — any payload with valid=False yields the
    same plan (invalid rows are excluded from candidate generation),
    and pinning the snapshot makes the bytes themselves deterministic,
    so the baseline replay applies the identical events."""
    out = []
    for r in np.asarray(rows).tolist():
        r = int(r)
        vals = {
            name: np.asarray(a)[[r]].copy()
            for name, a in p_cols.items()
        }
        vals["valid"] = np.zeros(1, np.bool_)
        out.append(StreamEvent(
            kind=kind, source=f"p{r}", seq=int(seq),
            provider_rows=np.asarray([r], np.int32), p_cols=vals,
            task_rows=np.zeros(0, np.int32), r_cols={},
        ))
    return out


def mass_leave_events(
    mass_index: int, rows, p_cols: dict
) -> list:
    """A fleet-level mass event's per-session decomposition: leave
    events at the mass tier. ``mass_index`` orders successive mass
    events (later index -> higher seq -> wins)."""
    return leave_events(
        rows, MASS_SEQ_BASE + int(mass_index), p_cols, kind="leave"
    )


def ejection_leave_events(
    generation: int, rows, p_cols: dict
) -> list:
    """A detector ejection's leave storm: one leave per source homed on
    the dead process, at the storm tier keyed by the post-ejection
    topology generation (monotone across successive ejections, and
    above every mass tier seq so 'the process died' beats 'the region
    blacked out' for a doubly-affected source)."""
    return leave_events(
        rows, STORM_SEQ_BASE + int(generation), p_cols, kind="leave"
    )


def pad_event(index: int) -> StreamEvent:
    """A cadence-advancing no-op event (zero rows): the driver pads the
    tail of a drilled run to the next reconcile boundary so the final
    answer is a RECONCILED plan comparable against the baseline's.
    Distinct seqs per pad keep the dedup ladder honest."""
    return StreamEvent(
        kind="heartbeat", source=PAD_SOURCE,
        seq=PAD_SEQ_BASE + int(index),
        provider_rows=np.zeros(0, np.int32), p_cols={},
        task_rows=np.zeros(0, np.int32), r_cols={},
    )


def blackout_storm_schedule(
    seed: int,
    shard: int,
    n_providers: int,
    frac: float = 0.1,
    mass_index: int = 0,
    tag: Optional[str] = None,
) -> dict:
    """The seeded leave-storm schedule a ``SessionFabric.blackout``
    arms (the faults/ composition satellite): which provider rows the
    regional blackout takes down, at which mass tier. A drill driver
    consumes this to mint :func:`mass_leave_events` into every
    session's stream — the blackout exercises the stream path, not
    just the refusal ladder. JSON-serializable (rides snapshots)."""
    rows = storm_rows(
        int(seed), tag or f"blackout-shard{int(shard)}",
        int(n_providers), float(frac),
    )
    return {
        "kind": "blackout",
        "seed": int(seed),
        "shard": int(shard),
        "mass_index": int(mass_index),
        "frac": float(frac),
        "rows": [int(r) for r in rows],
    }
