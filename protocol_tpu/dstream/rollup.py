"""Fleet-wide stream observability rollup.

The per-process ``/metrics.json`` snapshot carries a per-session
``"stream"`` section (event latency histogram + dedup / reconcile /
divergence counters) that the dfleet scrape join has so far ignored —
batch drills only read tick counters. :func:`stream_rollup` joins those
sections across a ``ProcessFleet.scrape()`` result into one fleet-wide
view for the loadgen report and the ``--dstream`` perf gate.

Pure function of the scrape dict — callable on live scrapes, on saved
report JSONs, and in tests without a fleet.
"""

from __future__ import annotations

from typing import Optional


def stream_rollup(scrapes: dict) -> dict:
    """Join the per-session ``"stream"`` sections of per-process
    ``/metrics.json`` snapshots into one fleet-wide aggregate.

    ``scrapes`` maps proc_id -> snapshot dict (or None for a dead /
    unscrapable process, as ``ProcessFleet.scrape`` returns). Counters
    (events, deduped, reconciled, divergence-row and repair-row totals)
    sum across the fleet; latency percentiles take the fleet max (an
    upper bound — per-proc histograms can't be re-merged exactly);
    ``sessions`` counts stream sections seen, ``procs`` lists per-proc
    breakdowns so a skewed process is visible in the report.
    """
    total = {
        "events": 0,
        "deduped": 0,
        "reconciled": 0,
        "divergence_rows_max": 0,
        "repair_rows": 0,
        "p99_us_max": 0.0,
        "max_us": 0.0,
        "sessions": 0,
    }
    procs = {}
    dead = []
    for proc_id, snap in (scrapes or {}).items():
        if not isinstance(snap, dict):
            dead.append(str(proc_id))
            continue
        agg = {
            "events": 0,
            "deduped": 0,
            "reconciled": 0,
            "divergence_rows_max": 0,
            "repair_rows": 0,
            "p99_us_max": 0.0,
            "max_us": 0.0,
            "sessions": 0,
        }
        # scraped /metrics.json nests per-session metrics under "obs";
        # a raw ObsRegistry.snapshot() has them at top level
        sessions_map = (
            (snap.get("obs") or {}).get("sessions")
            or snap.get("sessions") or {}
        )
        for s in sessions_map.values():
            st = (s or {}).get("stream")
            if not isinstance(st, dict):
                continue
            ev = st.get("event") or {}
            agg["sessions"] += 1
            agg["events"] += int(ev.get("count", 0))
            agg["deduped"] += int(st.get("deduped", 0))
            agg["reconciled"] += int(st.get("reconciled", 0))
            agg["repair_rows"] += int(st.get("repair_rows", 0))
            agg["divergence_rows_max"] = max(
                agg["divergence_rows_max"],
                int(st.get("divergence_rows_max", 0)),
            )
            agg["p99_us_max"] = max(
                agg["p99_us_max"], float(ev.get("p99_us", 0.0))
            )
            agg["max_us"] = max(
                agg["max_us"], float(ev.get("max_us", 0.0))
            )
        procs[str(proc_id)] = agg
        for k in ("events", "deduped", "reconciled", "repair_rows",
                  "sessions"):
            total[k] += agg[k]
        for k in ("divergence_rows_max", "p99_us_max", "max_us"):
            total[k] = max(total[k], agg[k])
    total["procs"] = procs
    total["dead_procs"] = dead
    return total


def events_per_second(
    rollup: dict, wall_s: Optional[float]
) -> float:
    """Fleet-wide server-observed event throughput for a drill wall."""
    if not wall_s or wall_s <= 0:
        return 0.0
    return float(rollup.get("events", 0)) / float(wall_s)
