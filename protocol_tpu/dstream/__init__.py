"""Distributed event firehose: streaming sessions x the process fleet.

PR 15's :mod:`protocol_tpu.stream` engine does sub-tick online repair
inside ONE session on ONE process; PR 12/14's :mod:`protocol_tpu.dfleet`
is batch-mode. This package composes them into the production shape the
reference's heartbeat architecture implies (PAPER.md §1: every worker
continuously heartbeats the orchestrator): event sources routed by the
consistent-hash ring to stream-mode wire-v2 sessions on every servicer
process, with three fleet-level capabilities:

  * **mass events** (:func:`fanout.mass_leave_events`) — one fleet-level
    event (a regional blackout, composed with the ``faults/`` blackout
    site) fans out deterministically to every affected session as
    per-source leave events at a SENTINEL seq tier, which restores the
    per-source supersession contract for mass events: convergence is
    independent of where the fan-out interleaves each session's
    firehose, so chaos'd delivery still converges bit-identical to
    fault-free replay;
  * **ejection storms** (:func:`fanout.ejection_leave_events`) — a
    detector ejection (PR 14) translates into leave events for every
    source homed on the dead process (:func:`fanout.source_home`),
    absorbed online by surviving sessions' stream engines — O(churned
    rows) per event, GapTracker certificate maintained — instead of
    waiting for a batch tick;
  * **live migration of streaming sessions** — the checkpoint journal
    now carries the FULL stream state (``StreamEngine.export_state``:
    dedup cursors, reconcile cadence cursor, counters), so the Migrate
    RPC path re-arms the engine at the target with zero dropped or
    double-applied events; a retransmit straddling the process boundary
    dedups at the target exactly as it would have at the origin.

Determinism contract: this package reads no clocks and no RNG state —
storm membership and source homing are pure sha1 functions of (seed,
tag, row) / the ring, the faults/plan idiom. Wall-clock scheduling
lives in the driver (fleet/loadgen), where it belongs.
"""

from protocol_tpu.dstream.fanout import (  # noqa: F401
    MASS_SEQ_BASE,
    PAD_SEQ_BASE,
    PAD_SOURCE,
    STORM_SEQ_BASE,
    affected_rows,
    blackout_storm_schedule,
    ejection_leave_events,
    leave_events,
    mass_leave_events,
    pad_event,
    source_home,
    storm_rows,
)
from protocol_tpu.dstream.rollup import stream_rollup  # noqa: F401
