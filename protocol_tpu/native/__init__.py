"""ctypes bindings for the native CPU assignment engine.

Builds native/assign_engine.cpp on demand (g++ -O3 -shared -fPIC, cached by
source mtime) and exposes numpy-friendly wrappers with the same contracts as
the JAX kernels in protocol_tpu.ops. This is the control plane's
no-accelerator fallback backend and the honest CPU baseline for bench.py —
the counterpart of the reference's in-process Rust matcher
(crates/orchestrator/src/scheduler/mod.rs).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "assign_engine.cpp")

# One shared library per build variant. The production build takes
# NATIVE_CFLAGS verbatim (same knob the Makefile honors); sanitizer
# variants pin -O1 -g so reports keep symbols/line numbers and the
# slowdown stays usable, and live in their own .so files so a sanitizer
# run never clobbers (or reuses) the production artifact.
_SO_VARIANTS = {
    "": "libassign_engine.so",
    "tsan": "libassign_engine.tsan.so",
    "asan": "libassign_engine.asan.so",
    # ISA variants: identical codegen (all per-ISA kernels are compiled
    # into every .so via target attributes), different BAKED default for
    # hosts with no env plumbing — the runtime dispatch still clamps to
    # what the CPU actually supports
    "avx2": "libassign_engine.avx2.so",
    "avx512": "libassign_engine.avx512.so",
}
_SANITIZE_FLAGS = {
    "tsan": ["-fsanitize=thread"],
    "asan": ["-fsanitize=address,undefined", "-fno-sanitize-recover=all"],
}
_ISA_VARIANT_FLAGS = {
    "avx2": ["-DENGINE_DEFAULT_ISA=1"],
    "avx512": ["-DENGINE_DEFAULT_ISA=2"],
}
# -march=x86-64-v2 (SSE4.2/POPCNT baseline, 2009+ hardware) instead of
# -march=native: a .so built on a dev box must load on any CI/prod host,
# and sanitizer builds want a stable ISA so reports reproduce across
# machines. Override via NATIVE_CFLAGS for tuned local builds.
# -ffp-contract=off: the per-ISA determinism contract demands that plain
# a*b+c NEVER silently fuses — every fma in the engine is an explicit
# fmaf/vfmadd, so each ISA has exactly one float pipeline regardless of
# compiler version or opt level.
_DEFAULT_CFLAGS = "-O3 -march=x86-64-v2 -ffp-contract=off"

_libs: dict[str, ctypes.CDLL] = {}

# runtime ISA codes — must match the kIsa* constants in assign_engine.cpp
ISA_NAMES = {0: "scalar", 1: "avx2", 2: "avx512"}
_ISA_CODES = {"scalar": 0, "avx2": 1, "avx512": 2}


class NativeBuildError(RuntimeError):
    pass


def sanitize_variant() -> str:
    """Active build variant from PROTOCOL_TPU_NATIVE_SANITIZE
    ("" | "tsan" | "asan"). Read per load() call, not at import, so the
    stress harness can select a variant for its child processes."""
    v = os.environ.get("PROTOCOL_TPU_NATIVE_SANITIZE", "").strip().lower()
    if v in ("", "0", "off", "none"):
        return ""
    if v not in _SANITIZE_FLAGS:
        raise NativeBuildError(
            f"PROTOCOL_TPU_NATIVE_SANITIZE must be tsan|asan, got {v!r}"
        )
    return v


def isa_request() -> Optional[str]:
    """Requested runtime ISA from PROTOCOL_TPU_NATIVE_ISA
    (scalar|avx2|avx512|auto), or None when unset — the loaded .so then
    keeps its baked default (scalar for the production build, so every
    committed golden stays valid without any env). ``auto`` requests the
    widest ISA and lets the engine clamp to host support. Read per
    load() call, like sanitize_variant()."""
    v = os.environ.get("PROTOCOL_TPU_NATIVE_ISA", "").strip().lower()
    if v == "":
        return None
    if v not in ("scalar", "avx2", "avx512", "auto"):
        raise NativeBuildError(
            "PROTOCOL_TPU_NATIVE_ISA must be scalar|avx2|avx512|auto, "
            f"got {v!r}"
        )
    return v


def isa_build_variant() -> str:
    """Baked-default build variant from PROTOCOL_TPU_NATIVE_ISA_VARIANT
    ("" | "avx2" | "avx512") — selects which .so load() uses when no
    sanitizer variant is active (sanitize wins: its .so carries all ISA
    kernels too, and the runtime env forces dispatch paths under the
    instrumented build)."""
    v = os.environ.get("PROTOCOL_TPU_NATIVE_ISA_VARIANT", "").strip().lower()
    if v in ("", "0", "off", "none"):
        return ""
    if v not in _ISA_VARIANT_FLAGS:
        raise NativeBuildError(
            f"PROTOCOL_TPU_NATIVE_ISA_VARIANT must be avx2|avx512, got {v!r}"
        )
    return v


def so_path(variant: str = "") -> str:
    return os.path.join(_REPO_ROOT, "native", _SO_VARIANTS[variant])


def _cflags(variant: str) -> list[str]:
    flags = os.environ.get("NATIVE_CFLAGS", _DEFAULT_CFLAGS).split()
    if variant in _SANITIZE_FLAGS:
        # sanitizer builds: drop the opt level (and any -march=native a
        # local override smuggled in) for -O1 -g + the sanitizer flags
        flags = [
            f for f in flags
            if not f.startswith("-O") and f != "-march=native"
        ]
        flags = ["-O1", "-g", *_SANITIZE_FLAGS[variant], *flags]
    elif variant in _ISA_VARIANT_FLAGS:
        flags = [*flags, *_ISA_VARIANT_FLAGS[variant]]
    return flags


def _build(variant: str = "") -> None:
    base = ["-std=gnu++17", "-pthread", "-shared", "-fPIC"]
    flags = _cflags(variant)
    cmd = ["g++", *flags, *base, "-o", so_path(variant), _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError as e:
        raise NativeBuildError(f"native engine build failed: {e}") from e
    except subprocess.CalledProcessError as e:
        march = [f for f in flags if f.startswith("-march=")]
        if not march:
            raise NativeBuildError(
                f"native engine build failed: {e.stderr}"
            ) from e
        # toolchains older than GCC 11 / Clang 12 may not know the
        # x86-64-v2 level name: retry portable (plain -O level)
        cmd = [
            "g++", *[f for f in flags if not f.startswith("-march=")],
            *base, "-o", so_path(variant), _SRC,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e2:
            raise NativeBuildError(
                f"native engine build failed: {e2.stderr}"
            ) from e2


def build(variant: str = "") -> str:
    """Build one variant unconditionally; returns the .so path (the
    sanitizer harness and Makefile parity entry point)."""
    if variant not in _SO_VARIANTS:
        raise NativeBuildError(
            f"unknown build variant {variant!r} "
            f"(want one of {sorted(_SO_VARIANTS)})"
        )
    _build(variant)
    return so_path(variant)


class _ProviderFeatures(ctypes.Structure):
    _fields_ = [
        ("gpu_count", ctypes.c_void_p),
        ("gpu_mem_mb", ctypes.c_void_p),
        ("gpu_model_id", ctypes.c_void_p),
        ("has_gpu", ctypes.c_void_p),
        ("has_cpu", ctypes.c_void_p),
        ("cpu_cores", ctypes.c_void_p),
        ("ram_mb", ctypes.c_void_p),
        ("storage_gb", ctypes.c_void_p),
        ("lat", ctypes.c_void_p),
        ("lon", ctypes.c_void_p),
        ("has_location", ctypes.c_void_p),
        ("price", ctypes.c_void_p),
        ("load", ctypes.c_void_p),
        ("valid", ctypes.c_void_p),
    ]


class _RequirementFeatures(ctypes.Structure):
    _fields_ = [
        ("cpu_required", ctypes.c_void_p),
        ("cpu_cores", ctypes.c_void_p),
        ("ram_mb", ctypes.c_void_p),
        ("storage_gb", ctypes.c_void_p),
        ("gpu_opt_valid", ctypes.c_void_p),
        ("gpu_count", ctypes.c_void_p),
        ("gpu_mem_min", ctypes.c_void_p),
        ("gpu_mem_max", ctypes.c_void_p),
        ("gpu_total_mem_min", ctypes.c_void_p),
        ("gpu_total_mem_max", ctypes.c_void_p),
        ("gpu_model_mask", ctypes.c_void_p),
        ("gpu_model_constrained", ctypes.c_void_p),
        ("lat", ctypes.c_void_p),
        ("lon", ctypes.c_void_p),
        ("has_location", ctypes.c_void_p),
        ("priority", ctypes.c_void_p),
        ("valid", ctypes.c_void_p),
    ]


def load() -> ctypes.CDLL:
    """Build (if stale) and load the engine. Raises NativeBuildError if no
    toolchain is available — callers fall back to the numpy/JAX paths.
    PROTOCOL_TPU_NATIVE_SANITIZE=tsan|asan selects the instrumented
    variant (run under the matching LD_PRELOADed runtime — see
    scripts/sanitize_native.py, which drives exactly that)."""
    variant = sanitize_variant() or isa_build_variant()
    isa = isa_request()  # parse (and reject bad values) before any work
    cached = _libs.get(variant)
    if cached is not None:
        if isa is not None:
            _apply_isa(cached, isa)
        return cached
    so = so_path(variant)
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(_SRC):
        _build(variant)
    lib = ctypes.CDLL(so)

    lib.engine_isa_supported.argtypes = [ctypes.c_int32]
    lib.engine_isa_supported.restype = ctypes.c_int32
    lib.engine_set_isa.argtypes = [ctypes.c_int32]
    lib.engine_set_isa.restype = ctypes.c_int32
    lib.engine_get_isa.argtypes = []
    lib.engine_get_isa.restype = ctypes.c_int32

    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")

    lib.greedy_assign.argtypes = [
        f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p, i32p
    ]
    lib.greedy_assign.restype = None
    lib.topk_candidates.argtypes = [
        f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, i32p, f32p
    ]
    lib.topk_candidates.restype = None
    lib.auction_sparse.argtypes = [
        i32p, f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int64, i32p,
    ]
    lib.auction_sparse.restype = ctypes.c_int32
    lib.fused_topk_candidates.argtypes = [
        ctypes.POINTER(_ProviderFeatures), ctypes.POINTER(_RequirementFeatures),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        i32p, f32p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.fused_topk_candidates.restype = None
    # the -mt variants take a trailing nullable EngineStats pointer
    # (ENGINE_STATS_SLOTS i64 slots — the observability plane's native
    # layer; see the per-kernel slot tables in assign_engine.cpp)
    lib.fused_topk_candidates_mt.argtypes = (
        lib.fused_topk_candidates.argtypes
        + [ctypes.c_int32, ctypes.c_void_p]
    )
    lib.fused_topk_candidates_mt.restype = None
    # v2: + use_buckets flag, coverage_frac, nullable rev_out (the
    # persistent [P, reverse_r] u64 reverse-edge keys the warm arena
    # carries), nullable slack tail ([T, slack] next-cheapest shadow —
    # the repair kernel's deletion absorber), nullable stats
    lib.fused_topk_candidates_v2.argtypes = (
        lib.fused_topk_candidates.argtypes
        + [ctypes.c_int32, ctypes.c_int32, ctypes.c_float,
           ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
           ctypes.c_void_p, ctypes.c_void_p]
    )
    lib.fused_topk_candidates_v2.restype = None
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    # incremental candidate repair: features + cand/rev/slack io + dirty
    # index sets + knobs + touched/changed masks + nullable stats
    lib.repair_topk_candidates_mt.argtypes = [
        ctypes.POINTER(_ProviderFeatures),
        ctypes.POINTER(_RequirementFeatures),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        i32p, f32p, u64p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int32, i32p, ctypes.c_int32, i32p, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float,
        u8p, u8p, ctypes.c_void_p,
    ]
    lib.repair_topk_candidates_mt.restype = ctypes.c_int32
    # ... plus the trailing nullable per-task outcome + margin buffers
    # (the decision-observability layer; null = zero overhead)
    lib.auction_sparse_mt.argtypes = [
        i32p, f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int64,
        ctypes.c_int32, f32p, u8p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_void_p, i32p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.auction_sparse_mt.restype = ctypes.c_int32
    lib.sinkhorn_sparse_mt.argtypes = [
        i32p, f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_int32, ctypes.c_float, ctypes.c_int32,
        f32p, f32p, ctypes.POINTER(ctypes.c_float), ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.sinkhorn_sparse_mt.restype = ctypes.c_int32
    _libs[variant] = lib
    if isa is not None:
        _apply_isa(lib, isa)
    return lib


def _apply_isa(lib: ctypes.CDLL, isa: str) -> None:
    """Force the engine's runtime ISA. ``auto`` requests the widest; the
    engine clamps every request to host support (graceful fallback: the
    call never fails, engine_get_isa reports what actually runs)."""
    lib.engine_set_isa(_ISA_CODES.get(isa, max(_ISA_CODES.values())))


def current_isa() -> str:
    """The ISA tag the engine is actually scoring with right now — the
    provenance value threaded through stats, obs, and trace frames."""
    return ISA_NAMES[int(load().engine_get_isa())]


def set_isa(isa: str) -> str:
    """Force the runtime ISA for this process (persisted via the env var
    so later load() calls — any variant — agree). Returns the EFFECTIVE
    ISA name after host-support clamping."""
    if isa not in ("scalar", "avx2", "avx512", "auto"):
        raise NativeBuildError(
            f"isa must be scalar|avx2|avx512|auto, got {isa!r}"
        )
    os.environ["PROTOCOL_TPU_NATIVE_ISA"] = isa
    load()
    return current_isa()


def isa_supported(isa: str) -> bool:
    """True when the host CPU (and build) can run ``isa`` exactly."""
    if isa == "auto":
        return True
    if isa not in _ISA_CODES:
        return False
    return bool(load().engine_isa_supported(_ISA_CODES[isa]))


# --------------- engine phase stats (observability plane) ---------------

# must match kEngineStatsSlots in assign_engine.cpp
ENGINE_STATS_SLOTS = 16

# --------------- per-task outcome taxonomy (quality plane) ---------------
#
# The decision-observability layer: what happened to each task, and by
# how much the winner won. Codes must match the engine's exit-loop
# assignment in assign_engine.cpp; the names are the wire/report
# vocabulary every layer above (arena stats, obs registry, trace
# outcome frames, the obs report's cause table) shares.
OUTCOME_ASSIGNED = 0
OUTCOME_NO_CANDIDATES = 1
OUTCOME_OUTBID = 2
OUTCOME_RETIRED = 3
OUTCOME_NAMES = {
    OUTCOME_ASSIGNED: "assigned",
    OUTCOME_NO_CANDIDATES: "unassigned:no_candidates",
    OUTCOME_OUTBID: "unassigned:outbid",
    OUTCOME_RETIRED: "unassigned:retired",
}

# per-kernel slot layouts: name -> slot index; *_ns slots are converted
# to *_ms float keys by _parse_stats
_FUSED_STATS = {
    "gen_fused_ns": 0, "gen_rev_merge_ns": 1, "gen_scatter_ns": 2,
    "gen_threads": 3,
    # capability-bucket pruner counters (0 when the pruner is off)
    "gen_visited": 4, "gen_pruned_rows": 5, "gen_fallback_rows": 6,
    "gen_bucket_ns": 7,
}
# incremental candidate repair (repair_topk_candidates_mt) — surfaced by
# the arena as eng_cand_repair_* / eng_cand_* scalars
_REPAIR_STATS = {
    "cand_repair_rows": 0, "cand_repair_rescans": 1,
    "cand_repair_cols": 2, "cand_repair_rev_rescans": 3,
    "cand_repair_visited": 4, "cand_repair_exact_scores": 5,
    "cand_repair_fallback_rows": 6,
    "cand_repair_col_ns": 7, "cand_repair_merge_ns": 8,
    "cand_repair_rev_ns": 9, "cand_repair_scatter_ns": 10,
    "cand_repair_compare_ns": 11, "cand_repair_threads": 12,
    "cand_repair_entrants": 13, "cand_repair_changed": 14,
    "cand_repair_touched": 15,
}
_AUCTION_STATS = {
    "rounds": 0, "bids": 1, "evicted": 2, "repair_passes": 3,
    "eps_phases": 4, "repair_ns": 5, "bid_ns": 6, "merge_ns": 7,
    "cleanup_ns": 8, "retired": 9, "quality_ns": 10,
    # duality-gap certificate addends, accumulated in the margin pass
    # (1e-6 cost units on the wire, floats after parsing; certificate
    # prices capped at the give-up magnitude — see the engine comment)
    "plan_cost_u6": 11, "idle_price_u6": 12, "cs_slack_u6": 13,
}
_SINKHORN_STATS = {
    "sink_iters": 0, "sink_csr_ns": 1, "sink_f_ns": 2, "sink_g_ns": 3,
    "sink_err_ns": 4, "sink_nnz": 5, "sink_quality_ns": 6,
}


def _outcome_bufs(outcomes, n_tasks: int) -> tuple:
    """(codes u8[T], margin f32[T], code ptr, margin ptr) for an
    outcomes dict request; all None when the caller passed None (the
    engine then skips the post-pass entirely)."""
    if outcomes is None:
        return None, None, None, None
    codes = np.zeros(n_tasks, np.uint8)
    margin = np.zeros(n_tasks, np.float32)
    return (
        codes, margin,
        codes.ctypes.data_as(ctypes.c_void_p),
        margin.ctypes.data_as(ctypes.c_void_p),
    )


def _stats_buf(stats) -> tuple:
    """(ndarray or None, ctypes pointer or None) for a stats dict."""
    if stats is None:
        return None, None
    buf = np.zeros(ENGINE_STATS_SLOTS, np.int64)
    return buf, buf.ctypes.data_as(ctypes.c_void_p)


def _parse_stats(stats: dict, buf, layout: dict) -> None:
    """Fold a filled slot buffer into the caller's dict: ``*_ns`` slots
    become ``*_ms`` floats (rounded to µs), counters stay ints. Repeat
    calls into the same dict ACCUMULATE (the arena's delta passes run
    the fused kernel more than once per solve)."""
    if buf is None:
        return
    # provenance tag: which float pipeline produced these numbers (and
    # the plan they describe) — threaded verbatim into arena last_stats,
    # obs /metrics.json, and trace OUTCOME frames
    stats["native_isa"] = current_isa()
    for name, slot in layout.items():
        v = int(buf[slot])
        if name.endswith("_ns"):
            key = name[:-3] + "_ms"
            stats[key] = round(stats.get(key, 0.0) + v / 1e6, 3)
        elif name.endswith("_u6"):
            # cost-unit scalars shipped as 1e-6 fixed point (i64 slots)
            key = name[:-3]
            stats[key] = round(stats.get(key, 0.0) + v / 1e6, 6)
        elif name.endswith("_threads"):
            stats[name] = v  # a setting, not a counter: last write wins
        else:
            stats[name] = stats.get(name, 0) + v


def available() -> bool:
    try:
        load()
        return True
    except NativeBuildError:
        return False


def greedy_assign(cost: np.ndarray, task_order: Optional[np.ndarray] = None) -> np.ndarray:
    lib = load()
    cost = np.ascontiguousarray(cost, np.float32)
    P, T = cost.shape
    out = np.empty(T, np.int32)
    if task_order is None:
        lib.greedy_assign(cost, P, T, None, out)
    else:
        order = np.ascontiguousarray(task_order, np.int32)
        lib.greedy_assign(cost, P, T, order.ctypes.data_as(ctypes.c_void_p), out)
    return out


def topk_candidates(cost: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
    lib = load()
    cost = np.ascontiguousarray(cost, np.float32)
    P, T = cost.shape
    k = min(k, P)
    cand_p = np.empty((T, k), np.int32)
    cand_c = np.empty((T, k), np.float32)
    lib.topk_candidates(cost, P, T, k, cand_p, cand_c)
    return cand_p, cand_c


def _marshal_features(p, r) -> tuple:
    """(pa, ra, pf, rf, P, T, K, W) for EncodedProviders /
    EncodedRequirements — the keep-alive lists MUST outlive the native
    call (the structs hold raw pointers into them)."""

    def i32(a):
        return np.ascontiguousarray(np.asarray(a), np.int32)

    def f32(a):
        return np.ascontiguousarray(np.asarray(a), np.float32)

    def u8(a):
        return np.ascontiguousarray(np.asarray(a), np.uint8)

    def u32(a):
        return np.ascontiguousarray(np.asarray(a), np.uint32)

    pa = [
        i32(p.gpu_count), i32(p.gpu_mem_mb), i32(p.gpu_model_id),
        u8(p.has_gpu), u8(p.has_cpu), i32(p.cpu_cores), i32(p.ram_mb),
        i32(p.storage_gb), f32(p.lat), f32(p.lon), u8(p.has_location),
        f32(p.price), f32(p.load), u8(p.valid),
    ]
    ra = [
        u8(r.cpu_required), i32(r.cpu_cores), i32(r.ram_mb),
        i32(r.storage_gb), u8(r.gpu_opt_valid), i32(r.gpu_count),
        i32(r.gpu_mem_min), i32(r.gpu_mem_max), i32(r.gpu_total_mem_min),
        i32(r.gpu_total_mem_max), u32(r.gpu_model_mask),
        u8(r.gpu_model_constrained), f32(r.lat), f32(r.lon),
        u8(r.has_location), f32(r.priority), u8(r.valid),
    ]
    pf = _ProviderFeatures(*[a.ctypes.data_as(ctypes.c_void_p) for a in pa])
    rf = _RequirementFeatures(*[a.ctypes.data_as(ctypes.c_void_p) for a in ra])
    return (
        pa, ra, pf, rf,
        pa[0].shape[0], ra[1].shape[0], ra[4].shape[1], ra[10].shape[2],
    )


def fused_topk_candidates(
    providers, requirements, weights=None, k: int = 64,
    reverse_r: int = 8, extra: int = 16, threads: Optional[int] = None,
    stats: Optional[dict] = None,
    bucketed: bool = False, coverage_frac: float = 0.6,
    rev_out: Optional[np.ndarray] = None,
    slack_out: Optional[tuple] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Fused cost + per-task top-k straight from encoded features — the
    degraded-mode twin of ops.sparse.candidates_topk_bidir (same jitter)
    that never materializes the [P, T] cost tensor. ``reverse_r``/
    ``extra`` enable the bidirectional completeness guarantee: EVERY
    provider's best-``reverse_r`` tasks are scattered into ``extra``
    appended columns (cheapest-first per task, forward dups dropped) so
    each provider has routes into the graph no matter how forward top-k
    windows pile up; 0 disables.

    ``providers`` / ``requirements`` are EncodedProviders /
    EncodedRequirements (numpy- or jax-backed); ``weights`` a CostWeights.
    Returns (cand_provider [T, k+extra] i32, cand_cost [T, k+extra] f32).

    ``threads``: None runs the historical single-threaded pass; an int
    routes through the multi-threaded engine (0 = all hardware threads),
    whose output is bit-identical for every thread count (contiguous task
    chunks + a deterministic reverse-edge merge).

    ``stats``: optional dict the call fills with engine phase stats
    (``gen_fused_ms`` / ``gen_rev_merge_ms`` / ``gen_scatter_ms`` /
    ``gen_threads``). Stats never feed solver state — results are
    bit-identical with or without them. Requesting stats routes through
    the -mt engine (at ``threads=1`` when none was asked for, which is
    bit-compatible with the single-threaded pass by the determinism
    contract).

    ``bucketed``: route each row through the capability-signature
    pruner — only the buckets whose (model, count) signature could
    satisfy one of the task's GPU alternatives are exact-scored, with a
    per-row full-scan fallback above ``coverage_frac``. Output is
    BIT-IDENTICAL to the unbucketed pass (pruned providers are provably
    infeasible); only the work shrinks.

    ``rev_out``: optional [P, reverse_r] u64 array the call fills with
    the per-provider reverse-edge keys — the persistent half of the
    warm arena's incrementally-repaired candidate structure.

    ``slack_out``: optional ``(slack_p [T, S] i32, slack_c [T, S] f32)``
    pair the call fills with each row's next-S-cheapest providers
    beyond the top-k — the repair kernel's deletion absorber (a
    departing top-k member is replaced from the slack instead of
    forcing a row re-score). Tracking the wider selection never
    changes the emitted top-k (the first k of a top-(k+S) selection IS
    the top-k).
    """
    lib = load()
    if weights is None:
        from protocol_tpu.ops.cost import CostWeights

        weights = CostWeights()

    # keep references alive for the duration of the call
    pa, ra, pf, rf, P, T, K, W = _marshal_features(providers, requirements)
    k = min(k, P)
    # persistent-output validation runs against the CALLER's declared
    # shapes, BEFORE the degenerate reset below zeroes reverse_r — an
    # empty batch must stay the documented quiet no-op, not a shape error
    if rev_out is not None:
        if rev_out.dtype != np.uint64 or rev_out.shape != (P, reverse_r):
            raise ValueError(
                f"rev_out must be uint64 [P={P}, reverse_r={reverse_r}], "
                f"got {rev_out.dtype} {rev_out.shape}"
            )
        if not rev_out.flags["C_CONTIGUOUS"]:
            raise ValueError("rev_out must be C-contiguous")
    slack_cap = 0
    if slack_out is not None:
        sp, sc = slack_out
        slack_cap = int(sp.shape[1])
        if (
            sp.dtype != np.int32 or sc.dtype != np.float32
            or sp.shape != (T, slack_cap) or sc.shape != sp.shape
            or not sp.flags["C_CONTIGUOUS"] or not sc.flags["C_CONTIGUOUS"]
        ):
            raise ValueError(
                "slack_out must be C-contiguous (i32 [T, S], f32 [T, S])"
            )
    if reverse_r <= 0 or extra <= 0 or k <= 0 or T <= 0:
        # degenerate shapes: the C++ pass early-returns without writing,
        # so extras must not allocate (np.empty garbage would flow into
        # the auction as out-of-range provider ids) and the persistent
        # outputs are padded HERE — empty lists, infeasible keys
        if rev_out is not None:
            # pack_key(kInfeasible, 0xffffffff): the engine's pad key
            b = np.uint64(
                np.float32(1e9).view(np.uint32) | np.uint32(0x80000000)
            )
            rev_out[...] = (b << np.uint64(32)) | np.uint64(0xFFFFFFFF)
            rev_out = None
        if slack_out is not None:
            slack_out[0][...] = -1
            slack_out[1][...] = np.float32(1e9)
            slack_out = None
            slack_cap = 0
        reverse_r = extra = 0
    cand_p = np.empty((T, k + extra), np.int32)
    cand_c = np.empty((T, k + extra), np.float32)
    args = (
        ctypes.byref(pf), ctypes.byref(rf), P, T, K, W, k,
        float(weights.price), float(weights.load),
        float(weights.proximity), float(weights.priority),
        cand_p, cand_c, reverse_r, extra,
    )
    if (
        threads is None and stats is None and not bucketed
        and rev_out is None and slack_out is None
    ):
        lib.fused_topk_candidates(*args)
    elif bucketed or rev_out is not None or slack_out is not None:
        buf, ptr = _stats_buf(stats)
        lib.fused_topk_candidates_v2(
            *args, int(1 if threads is None else threads),
            int(bool(bucketed)), float(coverage_frac),
            None if rev_out is None else rev_out.ctypes.data_as(
                ctypes.c_void_p
            ),
            slack_cap,
            None if slack_out is None else slack_out[0].ctypes.data_as(
                ctypes.c_void_p
            ),
            None if slack_out is None else slack_out[1].ctypes.data_as(
                ctypes.c_void_p
            ),
            ptr,
        )
        if stats is not None:
            _parse_stats(stats, buf, _FUSED_STATS)
    else:
        buf, ptr = _stats_buf(stats)
        lib.fused_topk_candidates_mt(
            *args, int(1 if threads is None else threads), ptr
        )
        if stats is not None:
            _parse_stats(stats, buf, _FUSED_STATS)
    return cand_p, cand_c


def repair_topk_candidates(
    providers, requirements, weights,
    cand_p: np.ndarray, cand_c: np.ndarray, rev: np.ndarray,
    dirty_p: np.ndarray, dirty_t: np.ndarray,
    k: int, reverse_r: int = 8, extra: int = 16, threads: int = 0,
    cheaper_tol: float = 0.05, coverage_frac: float = 0.6,
    slack: Optional[tuple] = None,
    stats: Optional[dict] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Incrementally repair a persistent candidate structure IN PLACE so
    it is bit-identical to a from-scratch
    ``fused_topk_candidates(..., rev_out=...)`` build on the CURRENT
    features — touching only the rows/columns the dirty provider/task
    index sets reach, never the full [P, T] matrix.

    ``cand_p`` [T, k+extra] i32 / ``cand_c`` [T, k+extra] f32 /
    ``rev`` [P, reverse_r] u64 are the structure built on the PREVIOUS
    features (which must differ from the current ones only at the dirty
    rows) and are rewritten in place. Returns ``(touched, changed)``
    bool [T] masks: rows whose content moved (the warm auction's
    repair_mask / seat-guard input) and rows whose membership changed or
    got materially cheaper (the retirement-clearing contract).

    ``slack``: optional persistent ``(slack_p [T, S] i32,
    slack_c [T, S] f32)`` pair from ``fused_topk_candidates``'s
    ``slack_out`` — the next-cheapest shadow that absorbs top-k
    deletions (a row only re-scores when it loses more members than the
    slack + entrants replace). Rewritten in place; lazily degraded
    (never part of the bit-identity contract, which covers cand + rev).

    Deterministic for every thread count; ``stats`` fills the
    ``cand_repair_*`` counters/walls (see ``_REPAIR_STATS``)."""
    lib = load()
    pa, ra, pf, rf, P, T, K, W = _marshal_features(providers, requirements)
    if cand_p.shape != (T, k + extra) or cand_c.shape != cand_p.shape:
        raise ValueError(
            f"cand arrays must be [T={T}, k+extra={k + extra}], got "
            f"{cand_p.shape} / {cand_c.shape}"
        )
    if rev.dtype != np.uint64 or rev.shape != (P, reverse_r):
        raise ValueError(
            f"rev must be uint64 [P={P}, reverse_r={reverse_r}], got "
            f"{rev.dtype} {rev.shape}"
        )
    for name, a in (("cand_p", cand_p), ("cand_c", cand_c), ("rev", rev)):
        if not a.flags["C_CONTIGUOUS"]:
            raise ValueError(f"{name} must be C-contiguous")
    slack_cap = 0
    if slack is not None:
        sp, sc = slack
        slack_cap = int(sp.shape[1])
        if (
            sp.dtype != np.int32 or sc.dtype != np.float32
            or sp.shape != (T, slack_cap) or sc.shape != sp.shape
            or not sp.flags["C_CONTIGUOUS"] or not sc.flags["C_CONTIGUOUS"]
        ):
            raise ValueError(
                "slack must be C-contiguous (i32 [T, S], f32 [T, S])"
            )
    # unique + sorted: a duplicated dirty id would sweep one column from
    # two threads (torn reverse list) and double-insert its forward
    # entrants (a dup inside one candidate row makes v1 == v2 in the
    # auction bid math) — dedup at the seam, not by caller convention
    dp = np.unique(np.asarray(dirty_p)).astype(np.int32)
    dt = np.unique(np.asarray(dirty_t)).astype(np.int32)
    touched = np.zeros(T, np.uint8)
    changed = np.zeros(T, np.uint8)
    buf, stats_ptr = _stats_buf(stats)
    rc = lib.repair_topk_candidates_mt(
        ctypes.byref(pf), ctypes.byref(rf), P, T, K, W, int(k),
        float(weights.price), float(weights.load),
        float(weights.proximity), float(weights.priority),
        cand_p, cand_c, rev,
        None if slack is None else slack[0].ctypes.data_as(ctypes.c_void_p),
        None if slack is None else slack[1].ctypes.data_as(ctypes.c_void_p),
        slack_cap,
        dp, int(dp.size), dt, int(dt.size),
        int(reverse_r), int(extra), int(threads),
        float(cheaper_tol), float(coverage_frac),
        touched, changed, stats_ptr,
    )
    if rc != 0:
        raise ValueError(f"repair_topk_candidates_mt rejected shapes (rc={rc})")
    if stats is not None:
        _parse_stats(stats, buf, _REPAIR_STATS)
    return touched.astype(bool), changed.astype(bool)


def auction_sparse(
    cand_provider: np.ndarray,
    cand_cost: np.ndarray,
    num_providers: int,
    eps_start: float = 4.0,
    eps_end: float = 0.02,
    scale: float = 0.25,
    max_events: int = 50_000_000,
) -> np.ndarray:
    lib = load()
    cand_p = np.ascontiguousarray(cand_provider, np.int32)
    cand_c = np.ascontiguousarray(cand_cost, np.float32)
    T, K = cand_p.shape
    out = np.empty(T, np.int32)
    lib.auction_sparse(
        cand_p, cand_c, num_providers, T, K,
        eps_start, eps_end, scale, max_events, out,
    )
    return out


def auction_sparse_mt(
    cand_provider: np.ndarray,
    cand_cost: np.ndarray,
    num_providers: int,
    eps_start: float = 4.0,
    eps_end: float = 0.02,
    scale: float = 0.25,
    max_events: int = 50_000_000,
    threads: int = 0,
    price: Optional[np.ndarray] = None,
    retired: Optional[np.ndarray] = None,
    seed_provider_for_task: Optional[np.ndarray] = None,
    max_release: int = 0,
    repair_mask: Optional[np.ndarray] = None,
    stats: Optional[dict] = None,
    outcomes: Optional[dict] = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic parallel auction (engine=native-mt): synchronous
    Jacobi bidding rounds — per-thread bid buffers against a shared price
    snapshot, merged by a deterministic reduction (highest increment wins,
    ties to the lowest task index). The matching is bit-identical for
    every thread count (threads=0 means all hardware threads).

    Carries the full dual state for warm chains: ``price`` [P] and
    ``retired`` [T] are consumed AND returned updated (pass None for a
    cold solve); ``seed_provider_for_task`` re-seats a previous matching
    (injective over >= 0 — duplicate seats keep the first). For a warm
    single-phase solve pass ``eps_start == eps_end``. The caller must
    clear ``retired`` flags for tasks whose candidates changed
    (ops/sparse.py assign_auction_sparse_warm has the same contract).

    ``max_release`` > 0 caps how many seats the eps-CS repair may evict
    per pass (the worst violators go first, deterministically): under
    heavy price/load drift an uncapped repair releases thousands of
    near-tie seats at once and the warm solve degenerates into a
    fine-eps cold auction. Capped, the re-optimization is amortized
    across solves while the matching stays feasible and injective.
    0 keeps the historical release-everything behavior.

    ``repair_mask`` [T] bool restricts the eps-CS repair to rows whose
    candidate costs the caller changed since the last converged solve —
    sound because prices are monotone (see the engine comment); None
    scans every row.

    ``stats``: optional dict filled with engine phase stats (``rounds``,
    ``bids``, ``evicted``, ``repair_passes``, ``eps_phases``,
    ``retired``, and ``repair_ms``/``bid_ms``/``merge_ms``/
    ``cleanup_ms`` phase walls). Stats never feed solver state.

    ``outcomes``: optional dict the call fills with the per-task
    decision taxonomy — ``codes`` (u8 [T]: ``OUTCOME_ASSIGNED`` /
    ``OUTCOME_NO_CANDIDATES`` / ``OUTCOME_OUTBID`` /
    ``OUTCOME_RETIRED``, see ``OUTCOME_NAMES``) and ``margin`` (f32 [T]:
    winner margin vs runner-up at final prices for assigned tasks, 0
    otherwise). Same contract as ``stats``: None means the engine skips
    the pass entirely, and the matching/prices/retirement are
    bit-identical with or without the buffers.

    Returns (provider_for_task [T] i32, price [P] f32, retired [T] bool).
    """
    lib = load()
    cand_p = np.ascontiguousarray(cand_provider, np.int32)
    cand_c = np.ascontiguousarray(cand_cost, np.float32)
    T, K = cand_p.shape
    price_io = (
        np.zeros(num_providers, np.float32)
        if price is None
        else np.array(price, np.float32, copy=True)
    )
    if price_io.shape[0] != num_providers:
        raise ValueError(
            f"price has {price_io.shape[0]} rows, want {num_providers}"
        )
    retired_io = (
        np.zeros(T, np.uint8)
        if retired is None
        else np.ascontiguousarray(np.asarray(retired, bool).astype(np.uint8))
    )
    if retired_io.shape[0] != T:
        raise ValueError(f"retired has {retired_io.shape[0]} rows, want {T}")
    seed_ptr = None
    seed_arr = None
    if seed_provider_for_task is not None:
        seed_arr = np.ascontiguousarray(seed_provider_for_task, np.int32)
        if seed_arr.shape[0] != T:
            raise ValueError(f"seed has {seed_arr.shape[0]} rows, want {T}")
        # clamp out-of-range seeds (same untrusted-input hygiene as the
        # gRPC warm path); the engine keeps the first of any duplicates
        seed_arr = np.where(
            (seed_arr >= 0) & (seed_arr < num_providers), seed_arr, -1
        ).astype(np.int32)
        seed_ptr = seed_arr.ctypes.data_as(ctypes.c_void_p)
    mask_ptr = None
    mask_arr = None
    if repair_mask is not None:
        mask_arr = np.ascontiguousarray(
            np.asarray(repair_mask, bool).astype(np.uint8)
        )
        if mask_arr.shape[0] != T:
            raise ValueError(
                f"repair_mask has {mask_arr.shape[0]} rows, want {T}"
            )
        mask_ptr = mask_arr.ctypes.data_as(ctypes.c_void_p)
    out = np.empty(T, np.int32)
    buf, stats_ptr = _stats_buf(stats)
    oc_codes, oc_margin, oc_ptr, mg_ptr = _outcome_bufs(outcomes, T)
    lib.auction_sparse_mt(
        cand_p, cand_c, num_providers, T, K,
        eps_start, eps_end, scale, max_events, int(threads),
        price_io, retired_io, seed_ptr, int(max_release), mask_ptr, out,
        stats_ptr, oc_ptr, mg_ptr,
    )
    if stats is not None:
        _parse_stats(stats, buf, _AUCTION_STATS)
    if outcomes is not None:
        outcomes["codes"] = oc_codes
        outcomes["margin"] = oc_margin
    return out, price_io, retired_io.astype(bool)


def sinkhorn_sparse_mt(
    cand_provider: np.ndarray,
    cand_cost: np.ndarray,
    num_providers: int,
    eps: float = 0.05,
    max_iters: int = 100,
    tol: float = 1e-3,
    threads: int = 0,
    f: Optional[np.ndarray] = None,
    g: Optional[np.ndarray] = None,
    stats: Optional[dict] = None,
    outcomes: Optional[dict] = None,
) -> tuple[np.ndarray, np.ndarray, int, float]:
    """One eps phase of the sparse multi-threaded Sinkhorn engine
    (engine=sinkhorn-mt): log-domain entropic OT restricted to the top-K
    candidate edges — O(nnz) per iteration instead of the blocked JAX
    kernel's O(P*T) dense tile sweeps (the 100k x 100k rc=143 killer).

    ``f`` [P] / ``g`` [T] are DUAL potentials in cost units, consumed AND
    returned updated (pass None for a cold start): they carry unchanged
    across eps-annealing phases and across warm re-solves after churn —
    the plan exp((f+g-c)/eps) is invariant under the uniform shift
    (f-s, g+s), the same soundness argument as the warm auction's price
    downshift. The result is BIT-IDENTICAL for every thread count (each
    row/column is reduced serially by one thread in a fixed edge order)
    and matches :func:`protocol_tpu.ops.sparse.sinkhorn_potentials_sparse_np`
    up to libm ulps.

    Iterates until the provider-marginal drift falls below ``tol`` or
    ``max_iters`` runs out (task marginals are exact after every update).

    ``outcomes``: optional dict filled with the entropic-layer taxonomy
    — ``codes`` (u8 [T]: 0 = feasible candidate support,
    ``OUTCOME_NO_CANDIDATES`` = the plan cannot touch the task) and
    ``margin`` (f32 [T]: argmax margin of ``f_p - c`` over the task's
    candidates at the final potentials, in cost units). The injective
    seat taxonomy comes from the auction referee downstream; None means
    zero overhead and bit-identical potentials.

    Returns (f, g, iterations_run, final_marginal_err).
    """
    lib = load()
    if not float(eps) > 0.0:
        # eps = 0 turns the engine's 1/eps into inf and fills the
        # potentials with NaN; refuse at the seam
        raise ValueError(f"eps must be > 0, got {eps}")
    cand_p = np.ascontiguousarray(cand_provider, np.int32)
    cand_c = np.ascontiguousarray(cand_cost, np.float32)
    T, K = cand_p.shape
    f_io = (
        np.zeros(num_providers, np.float32)
        if f is None
        else np.array(f, np.float32, copy=True)
    )
    if f_io.shape[0] != num_providers:
        raise ValueError(f"f has {f_io.shape[0]} rows, want {num_providers}")
    g_io = (
        np.zeros(T, np.float32)
        if g is None
        else np.array(g, np.float32, copy=True)
    )
    if g_io.shape[0] != T:
        raise ValueError(f"g has {g_io.shape[0]} rows, want {T}")
    err = ctypes.c_float(0.0)
    buf, stats_ptr = _stats_buf(stats)
    oc_codes, oc_margin, oc_ptr, mg_ptr = _outcome_bufs(outcomes, T)
    iters = lib.sinkhorn_sparse_mt(
        cand_p, cand_c, num_providers, T, K,
        float(eps), int(max_iters), float(tol), int(threads),
        f_io, g_io, ctypes.byref(err), stats_ptr, oc_ptr, mg_ptr,
    )
    if stats is not None:
        _parse_stats(stats, buf, _SINKHORN_STATS)
    if outcomes is not None:
        outcomes["codes"] = oc_codes
        outcomes["margin"] = oc_margin
    return f_io, g_io, int(iters), float(err.value)


def sinkhorn_sparse_anneal(
    cand_provider: np.ndarray,
    cand_cost: np.ndarray,
    num_providers: int,
    eps_start: float = 1.0,
    eps_end: float = 0.05,
    scale: float = 0.25,
    iters_per_phase: int = 50,
    tol: float = 1e-3,
    threads: int = 0,
    f: Optional[np.ndarray] = None,
    g: Optional[np.ndarray] = None,
    phase_stats: Optional[list] = None,
    stats: Optional[dict] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Epsilon-annealing ladder over :func:`sinkhorn_sparse_mt`: geometric
    eps descent (eps_start -> eps_end by ``scale``) with the dual
    potentials carried across phases verbatim — coarse phases place the
    bulk of the mass in a handful of cheap iterations, fine phases only
    sharpen it (the entropic twin of the auction's eps-scaling).

    ``phase_stats`` (a list, appended in place) records per-phase
    ``{"eps", "iters", "err", "wall_s"}`` — the ladder-#3 artifact's
    wall-clock-per-anneal-phase evidence. Returns (f, g)."""
    import time as _time

    if not (float(eps_end) > 0.0 and float(eps_start) > 0.0):
        # eps_end <= 0 is unreachable by geometric descent: the ladder
        # would burn ~1200 futile phases until eps underflows to exactly
        # 0.0 and the engine's 1/eps goes inf (NaN potentials) — refuse
        # up front, like sinkhorn_sparse_mt itself
        raise ValueError(
            f"eps_start/eps_end must be > 0, got {eps_start}/{eps_end}"
        )
    if eps_start < eps_end:
        # an ascending pair would silently run ONE phase at eps_start and
        # return un-annealed potentials — a swapped-argument bug, not a
        # configuration; refuse like the other misconfigurations
        raise ValueError(
            f"eps_start ({eps_start}) must be >= eps_end ({eps_end})"
        )
    if eps_start > eps_end and not (0.0 < scale < 1.0):
        # the ladder only terminates by eps DESCENDING to eps_end: a
        # non-contracting scale would spin phases forever (and in the
        # gRPC servicer, forever while holding a session lock and a
        # thread-budget grant)
        raise ValueError(
            f"scale must be in (0, 1) when eps_start > eps_end, got {scale}"
        )
    eps = float(eps_start)
    while True:
        t0 = _time.perf_counter()
        f, g, iters, err = sinkhorn_sparse_mt(
            cand_provider, cand_cost, num_providers,
            eps=eps, max_iters=iters_per_phase, tol=tol, threads=threads,
            f=f, g=g, stats=stats,
        )
        if phase_stats is not None:
            phase_stats.append({
                "eps": round(eps, 6),
                "iters": iters,
                "err": round(err, 6),
                "wall_s": round(_time.perf_counter() - t0, 4),
            })
        if eps <= eps_end:
            return f, g
        eps = max(eps * scale, float(eps_end))


def sinkhorn_referee_prices(
    f: np.ndarray,
    cand_provider: np.ndarray,
    cand_cost: np.ndarray,
) -> np.ndarray:
    """Auction-referee seed prices from the Sinkhorn provider duals:
    ``price = max(f) - f``, capped at ``max_cost + 5``.

    The plan prefers exactly the edges maximizing f_p - c, which is the
    auction's value ordering under price = -f; the uniform downshift by
    max(f) keeps prices nonnegative without changing a single price
    DIFFERENCE (shift invariance — the same soundness argument as the
    warm auction's price downshift). The CAP keeps every provider
    biddable: on a support whose uniform marginals are infeasible, the
    duals of unreachable provider pockets diverge toward -inf, and an
    uncapped spread pushes their tasks past the referee's give-up floor
    (-(2*max_cost + 10)) before a single bid — measured ~10% assignment
    loss at 512. With the cap at max_cost + 5, every feasible edge's
    value stays above give-up, so retirement can only come from real
    bidding, never from the seed. (Unlike the r5 warm-price-clamp
    pathology this flattens only the DIVERGED tail — converged duals
    live within the cost scale and keep their differences.)

    This is the ONE home of the seeding formula — the arena, the perf
    gate, the stage-S script, and bench_scaling all call it, so a change
    to the give-up floor or the cap can never leave a gate measuring a
    stale seeding."""
    # lazy import: ops.cost pulls in jax, which this module must not do
    # at import time (control-plane processes load it with no backend)
    from protocol_tpu.ops.cost import INFEASIBLE

    f = np.asarray(f, np.float32)
    if f.size == 0:
        return np.zeros(0, np.float32)
    cand_p = np.asarray(cand_provider)
    cand_c = np.asarray(cand_cost)
    # the SAME feasibility cutoff the engine and the auction use
    # (kInfeasible * 0.5): a narrower cutoff would compute max_cost over
    # fewer edges than the referee bids on and the cap would clamp
    # converged duals it promises to preserve
    feas = (cand_p >= 0) & (cand_c < INFEASIBLE * 0.5)
    max_cost = float(cand_c[feas].max()) if feas.any() else 0.0
    return np.minimum(
        np.float32(f.max()) - f, np.float32(max_cost + 5.0)
    )
