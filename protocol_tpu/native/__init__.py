"""ctypes bindings for the native CPU assignment engine.

Builds native/assign_engine.cpp on demand (g++ -O3 -shared -fPIC, cached by
source mtime) and exposes numpy-friendly wrappers with the same contracts as
the JAX kernels in protocol_tpu.ops. This is the control plane's
no-accelerator fallback backend and the honest CPU baseline for bench.py —
the counterpart of the reference's in-process Rust matcher
(crates/orchestrator/src/scheduler/mod.rs).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "assign_engine.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libassign_engine.so")

_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", _SO, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        detail = getattr(e, "stderr", str(e))
        raise NativeBuildError(f"native engine build failed: {detail}") from e


def load() -> ctypes.CDLL:
    """Build (if stale) and load the engine. Raises NativeBuildError if no
    toolchain is available — callers fall back to the numpy/JAX paths."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        _build()
    lib = ctypes.CDLL(_SO)

    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")

    lib.greedy_assign.argtypes = [
        f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p, i32p
    ]
    lib.greedy_assign.restype = None
    lib.topk_candidates.argtypes = [
        f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, i32p, f32p
    ]
    lib.topk_candidates.restype = None
    lib.auction_sparse.argtypes = [
        i32p, f32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int64, i32p,
    ]
    lib.auction_sparse.restype = ctypes.c_int32
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except NativeBuildError:
        return False


def greedy_assign(cost: np.ndarray, task_order: Optional[np.ndarray] = None) -> np.ndarray:
    lib = load()
    cost = np.ascontiguousarray(cost, np.float32)
    P, T = cost.shape
    out = np.empty(T, np.int32)
    if task_order is None:
        lib.greedy_assign(cost, P, T, None, out)
    else:
        order = np.ascontiguousarray(task_order, np.int32)
        lib.greedy_assign(cost, P, T, order.ctypes.data_as(ctypes.c_void_p), out)
    return out


def topk_candidates(cost: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
    lib = load()
    cost = np.ascontiguousarray(cost, np.float32)
    P, T = cost.shape
    k = min(k, P)
    cand_p = np.empty((T, k), np.int32)
    cand_c = np.empty((T, k), np.float32)
    lib.topk_candidates(cost, P, T, k, cand_p, cand_c)
    return cand_p, cand_c


def auction_sparse(
    cand_provider: np.ndarray,
    cand_cost: np.ndarray,
    num_providers: int,
    eps_start: float = 4.0,
    eps_end: float = 0.02,
    scale: float = 0.25,
    max_events: int = 50_000_000,
) -> np.ndarray:
    lib = load()
    cand_p = np.ascontiguousarray(cand_provider, np.int32)
    cand_c = np.ascontiguousarray(cand_cost, np.float32)
    T, K = cand_p.shape
    out = np.empty(T, np.int32)
    lib.auction_sparse(
        cand_p, cand_c, num_providers, T, K,
        eps_start, eps_end, scale, max_events, out,
    )
    return out
