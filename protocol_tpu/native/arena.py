"""Persistent warm-solve arena for the native CPU engine (engine=native-mt).

The degraded-mode twin of the CandidateCache + warm-kernel pipeline
(sched/cand_cache.py + ops/sparse.assign_auction_sparse_warm): repeated
solves against an incrementally-churned marketplace reuse everything that
survives between ticks instead of rebuilding it —

  - **Candidate structure.** The fused cost+top-k pass is the dominant
    stage (~90% of a cold native solve). The arena keeps the assembled
    [T, k+extra] bidirectional candidate lists and, on churn, recomputes
    only the rows that can have changed: dirty TASKS get a fresh fused
    pass against the full fleet; dirty PROVIDERS are dropped from every
    cached list and re-merged from one [dirty-P x T] delta pass (their
    forward candidates AND their reverse edges) — never the full pass.
  - **Auction dual state.** Prices per provider, the retirement mask per
    task, and the previous matching are carried into a single-phase warm
    auction (native.auction_sparse_mt), whose eps-CS repair evicts stale
    seeds. Retirement flags are cleared for exactly the rows whose
    candidates changed — the same caller contract the JAX warm kernel
    documents ("rows whose costs or candidates changed must be cleared").

Dirty detection is value-based: each provider/requirement feature column
is compared row-wise against the previous solve's columns, so any change
that can affect feasibility or cost (specs, price, load, validity, the
requirement DSL fields) marks its row dirty and ONLY that row is
recomputed. Two staleness backstops mirror the TPU path: a dirty fraction
above ``max_dirty_frac`` triggers a full rebuild (the delta pass would
cost more than it saves), and ``cold_every`` bounds tie-jitter drift from
delta passes (delta candidates are jittered by their local indices, like
the CandidateCache's merge batches) plus the warm chain's monotone price
ratchet.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from protocol_tpu import native, obs
from protocol_tpu.obs import quality as _quality
from protocol_tpu.obs.spans import TRACER as _tracer

# canonical dtypes per encoded field (mirrors native.fused_topk_candidates'
# coercions so comparing cached vs incoming columns is exact)
_P_SPEC = (
    ("gpu_count", np.int32), ("gpu_mem_mb", np.int32),
    ("gpu_model_id", np.int32), ("has_gpu", np.uint8),
    ("has_cpu", np.uint8), ("cpu_cores", np.int32), ("ram_mb", np.int32),
    ("storage_gb", np.int32), ("lat", np.float32), ("lon", np.float32),
    ("has_location", np.uint8), ("price", np.float32), ("load", np.float32),
    ("valid", np.uint8),
)
_R_SPEC = (
    ("cpu_required", np.uint8), ("cpu_cores", np.int32), ("ram_mb", np.int32),
    ("storage_gb", np.int32), ("gpu_opt_valid", np.uint8),
    ("gpu_count", np.int32), ("gpu_mem_min", np.int32),
    ("gpu_mem_max", np.int32), ("gpu_total_mem_min", np.int32),
    ("gpu_total_mem_max", np.int32), ("gpu_model_mask", np.uint32),
    ("gpu_model_constrained", np.uint8), ("lat", np.float32),
    ("lon", np.float32), ("has_location", np.uint8),
    ("priority", np.float32), ("valid", np.uint8),
)


def _canon(enc, spec) -> dict[str, np.ndarray]:
    return {
        name: np.ascontiguousarray(np.asarray(getattr(enc, name)), dtype)
        for name, dtype in spec
    }


def _dirty_rows(new: dict, old: dict, spec) -> np.ndarray:
    """Row-wise OR of per-field inequality (trailing axes collapsed)."""
    n = new[spec[0][0]].shape[0]
    dirty = np.zeros(n, bool)
    for name, _ in spec:
        diff = new[name] != old[name]
        dirty |= diff.reshape(n, -1).any(axis=1)
    return dirty


def _subset(fields: dict, idx: np.ndarray, spec) -> object:
    """A namespace with the gathered rows of each field (duck-types the
    Encoded* dataclasses for native.fused_topk_candidates)."""
    ns = type("_Sub", (), {})()
    for name, _ in spec:
        setattr(ns, name, fields[name][idx])
    return ns


def _as_ns(fields: dict, spec) -> object:
    ns = type("_Full", (), {})()
    for name, _ in spec:
        setattr(ns, name, fields[name])
    return ns


class NativeSolveArena:
    def __init__(
        self,
        k: int = 64,
        reverse_r: int = 8,
        extra: int = 16,
        threads: int = 0,
        cold_every: int = 256,
        max_dirty_frac: float = 0.25,
        eps_start: float = 4.0,
        eps_end: float = 0.02,
        max_release: int = 64,
        dual_refresh_every: int = 16,
        warm_eps_start: float = 0.32,
        engine: str = "auction",
        sink_eps_start: float = 1.0,
        sink_eps_end: float = 0.05,
        sink_scale: float = 0.25,
        sink_iters: int = 50,
        # marginal-drift tolerance: the rounding referee consumes the
        # plan's ARGMAX structure, which stabilizes one to two orders
        # before the marginals polish — 1e-2 halves the iteration bill
        # with no measured effect on the rounded matching
        sink_tol: float = 1e-2,
    ):
        if engine not in ("auction", "sinkhorn"):
            raise ValueError(
                f"engine must be auction|sinkhorn, got {engine!r}"
            )
        self.k = k
        self.reverse_r = reverse_r
        self.extra = extra
        self.threads = threads
        self.cold_every = cold_every
        self.max_dirty_frac = max_dirty_frac
        self.eps_start = eps_start
        self.eps_end = eps_end
        # Solve engine over the (shared) candidate structure:
        #   "auction"   the eps-scaled Jacobi auction with full dual carry
        #               (prices + retirement + matching) — the PR-1 path.
        #   "sinkhorn"  sparse entropic OT (native.sinkhorn_sparse_mt):
        #               O(nnz) log-domain potentials annealed over an eps
        #               ladder, warm (f, g) carry across churn (uniform-
        #               shift invariant, so carried potentials are sound),
        #               then INJECTIVE rounding by the sparse auction as
        #               referee — seeded with price = max(f) - f, so the
        #               referee starts from the entropic solution's global
        #               prices and converges in a handful of rounds.
        self.engine = engine
        self.sink_eps_start = sink_eps_start
        self.sink_eps_end = sink_eps_end
        self.sink_scale = sink_scale
        self.sink_iters = sink_iters
        self.sink_tol = sink_tol
        # warm-solve eviction cap (native.auction_sparse_mt max_release):
        # bounds the per-solve re-bidding wave under drift; re-ranked every
        # solve so staleness is amortized, and cold_every re-grounds fully
        self.max_release = max_release
        # Dual refresh: the warm chain's price ratchet is monotone, so
        # war losers retire and STAY retired while idle providers
        # accumulate — measured ~14 lost assignments per tick at 16k
        # under 1% churn, with no plateau. Every ``dual_refresh_every``
        # warm solves the auction re-runs with fresh prices/retirement
        # over the CACHED candidate structure (the expensive part is
        # kept): cardinality snaps back to the cold solve's level and the
        # amortized cost is a few tens of ms per tick. cold_every still
        # re-grounds the structure itself.
        self.dual_refresh_every = dual_refresh_every
        # Warm solves open at a COARSE eps and scale down (0.32 -> 0.08 ->
        # eps_end by the engine's 0.25 scale): evicted seats separate from
        # rivals in a handful of coarse rounds instead of thousands of
        # eps_end-increment bidding-war rounds. Measured at 16k/1% churn:
        # 182 -> 107 ms mean tick at a ~1 point cardinality-floor cost
        # (the dual refresh re-grounds the floor every cycle). Set to
        # eps_end for the historical single-fine-phase behavior.
        self.warm_eps_start = warm_eps_start
        self.last_stats: dict = {}
        self.invalidate()

    @property
    def price(self) -> Optional[np.ndarray]:
        """Carried auction prices [P] after the last solve (dual state)."""
        return self._price

    @property
    def retired(self) -> Optional[np.ndarray]:
        """Carried retirement mask [T] after the last solve."""
        return self._retired

    @property
    def potentials(self) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Carried Sinkhorn dual potentials (f [P], g [T]) after the last
        solve — (None, None) on the auction engine / before any solve."""
        return self._f, self._g

    def export_state(self) -> Optional[dict]:
        """The carried warm state as a flat dict of scalars and arrays —
        everything the next solve's trajectory depends on: the candidate
        structure (path-dependent: incremental merges reorder lists, so
        regenerating it cold would NOT reproduce the warm chain), the
        auction/sinkhorn duals, the previous matching, the shadow
        columns' role is played by the caller (who must restore the same
        columns), and the cadence cursors (``warm_solves`` drives
        ``cold_every``, ``dual_age`` drives ``dual_refresh_every`` — a
        restore that dropped them would re-ground on a different tick).

        Returns None before any solve (nothing carried: a restore would
        just be a cold arena). Arrays are copies — a checkpoint must not
        alias live solver state."""
        if self._cand_p is None:
            return None

        def _c(a):
            return None if a is None else np.array(a, copy=True)

        out = {
            "cand_p": _c(self._cand_p),
            "cand_c": _c(self._cand_c),
            "price": _c(self._price),
            "retired": _c(self._retired),
            "p4t": _c(self._p4t),
            "f": _c(self._f),
            "g": _c(self._g),
            "starve_age": _c(self._starve_age),
            "warm_solves": int(self._warm_solves),
            "dual_age": int(self._dual_age),
            "weights_key": tuple(self._weights_key),
        }
        # the arena's OWN dirty-detection baseline (it can lag the
        # session's current columns when degraded ticks applied deltas
        # without solving): restoring the session columns as the
        # baseline would silently swallow that accumulated churn
        for name, _ in _P_SPEC:
            out[f"pf_{name}"] = _c(self._p_fields[name])
        for name, _ in _R_SPEC:
            out[f"rf_{name}"] = _c(self._r_fields[name])
        return out

    def restore_state(self, ep, er, state: dict) -> None:
        """Rehydrate the warm chain from :meth:`export_state` output plus
        the exact columns (``ep``/``er``) the exporting arena last
        solved. The next ``solve`` continues the chain bit-identically:
        dirty detection diffs against these columns, the candidate
        structure and duals are the exported ones, and the cadence
        cursors resume mid-schedule. The arena's construction params
        (k / eps ladder / engine / refresh cadences) must match the
        exporter's — the checkpoint layer persists and re-applies them."""
        self.invalidate()
        if "pf_gpu_count" in state:
            # exported baseline columns win (see export_state: they can
            # lag the caller's current columns after degraded ticks)
            self._p_fields = {
                name: np.array(state[f"pf_{name}"], copy=True)
                for name, _ in _P_SPEC
            }
            self._r_fields = {
                name: np.array(state[f"rf_{name}"], copy=True)
                for name, _ in _R_SPEC
            }
        else:
            self._p_fields = _canon(ep, _P_SPEC)
            self._r_fields = _canon(er, _R_SPEC)
        self._cand_p = np.array(state["cand_p"], copy=True)
        self._cand_c = np.array(state["cand_c"], copy=True)
        for name in ("price", "retired", "p4t", "f", "g", "starve_age"):
            v = state.get(name)
            setattr(
                self, f"_{name}",
                None if v is None else np.array(v, copy=True),
            )
        self._warm_solves = int(state["warm_solves"])
        self._dual_age = int(state["dual_age"])
        self._weights_key = tuple(state["weights_key"])

    def invalidate(self) -> None:
        """Drop all carried state: the next solve is cold."""
        self._p_fields: Optional[dict] = None
        self._r_fields: Optional[dict] = None
        self._weights_key: Optional[tuple] = None
        self._cand_p: Optional[np.ndarray] = None
        self._cand_c: Optional[np.ndarray] = None
        self._price: Optional[np.ndarray] = None
        self._retired: Optional[np.ndarray] = None
        self._p4t: Optional[np.ndarray] = None
        self._f: Optional[np.ndarray] = None  # sinkhorn provider duals
        self._g: Optional[np.ndarray] = None  # sinkhorn task duals
        self._sink_stats: dict = {}
        self._warm_solves = 0
        self._dual_age = 0
        # quality plane (obs): per-task consecutive-unassigned ages and
        # the last computed quality scalars (reused verbatim by the
        # byte-identical short-circuit tick — nothing changed, so the
        # gap/outcome certificate is still exact)
        self._starve_age: Optional[np.ndarray] = None
        self._last_quality: dict = {}

    # ---------------- internals ----------------

    @staticmethod
    def _wkey(weights) -> tuple:
        return (
            float(weights.price), float(weights.load),
            float(weights.proximity), float(weights.priority),
        )

    def _shapes_compatible(self, pf: dict, rf: dict) -> bool:
        old_p, old_r = self._p_fields, self._r_fields
        if old_p is None or old_r is None:
            return False
        return all(
            pf[n].shape == old_p[n].shape for n, _ in _P_SPEC
        ) and all(rf[n].shape == old_r[n].shape for n, _ in _R_SPEC)

    def _sinkhorn_round(
        self,
        P: int,
        warm: bool,
        retired: Optional[np.ndarray] = None,
        seed: Optional[np.ndarray] = None,
        max_release: int = 0,
        eng: Optional[dict] = None,
        outs: Optional[dict] = None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The sinkhorn engine's solve stage over the CURRENT cached
        candidate structure: entropic potentials (cold: the full anneal
        ladder from zero duals; warm: one fine-eps phase from the carried
        (f, g) — churn only perturbs the fixed point, so a handful of
        O(nnz) iterations re-converge it), then injective rounding by the
        sparse auction referee seeded with price = max(f) - f. The uniform
        downshift keeps referee prices nonnegative and far from the
        give-up floor without changing a single price DIFFERENCE — the
        same soundness argument as the warm auction's price downshift.

        The referee's eps-CS repair runs over ALL rows (repair_mask=None):
        unlike the auction engine's carried prices, referee prices are
        re-derived from the (globally shifted) potentials each solve, so
        "only churned rows can have degraded" does not hold; the full
        [T x K] repair scan is one pass over the candidate structure —
        noise next to the potential iterations. ``max_release`` still caps
        the eviction wave.
        """
        phase_stats: list = []
        carried = (
            warm
            and self._f is not None
            and self._f.shape[0] == P
            and self._g is not None
            and self._g.shape[0] == self._cand_p.shape[0]
        )
        if carried:
            f, g, iters, err = native.sinkhorn_sparse_mt(
                self._cand_p, self._cand_c, P,
                eps=self.sink_eps_end, max_iters=self.sink_iters,
                tol=self.sink_tol, threads=self.threads,
                f=self._f, g=self._g, stats=eng,
            )
            phase_stats.append({
                "eps": self.sink_eps_end, "iters": iters,
                "err": round(err, 6), "warm": True,
            })
        else:
            f, g = native.sinkhorn_sparse_anneal(
                self._cand_p, self._cand_c, P,
                eps_start=self.sink_eps_start, eps_end=self.sink_eps_end,
                scale=self.sink_scale, iters_per_phase=self.sink_iters,
                tol=self.sink_tol, threads=self.threads,
                phase_stats=phase_stats, stats=eng,
            )
        self._f, self._g = f, g
        self._sink_stats = {
            "sinkhorn_phases": len(phase_stats),
            "sinkhorn_iters": int(sum(s["iters"] for s in phase_stats)),
            "sinkhorn_err": phase_stats[-1]["err"] if phase_stats else None,
        }
        # Referee seed prices from the provider duals — downshifted and
        # capped below the give-up floor; the formula and its soundness
        # argument live in native.sinkhorn_referee_prices (the one home
        # shared with the perf gate, stage-S script, and bench)
        price0 = native.sinkhorn_referee_prices(
            f, self._cand_p, self._cand_c
        )
        return native.auction_sparse_mt(
            self._cand_p, self._cand_c, num_providers=P,
            eps_start=max(self.warm_eps_start, self.eps_end),
            eps_end=self.eps_end,
            threads=self.threads,
            price=price0, retired=retired,
            seed_provider_for_task=seed, max_release=max_release,
            stats=eng, outcomes=outs,
        )

    def _quality_pass(
        self,
        rf: dict,
        p4t: np.ndarray,
        price: Optional[np.ndarray],
        prev_p4t: Optional[np.ndarray],
        outs: Optional[dict],
        eng: Optional[dict] = None,
    ) -> dict:
        """The decision-quality record for one solve (obs plane on):
        certified duality gap from the carried duals, plan churn vs the
        previous tick, starvation ages, and the native outcome taxonomy
        — flat scalars for ``last_stats`` (wall in ``quality_ms``).
        Timings and certificates ride NEXT TO the result, never into
        it."""
        t0 = time.perf_counter()
        stats, self._starve_age = _quality.tick_quality(
            self._cand_p, self._cand_c, p4t, price,
            valid=rf["valid"].astype(bool),
            prev_p4t=prev_p4t,
            starve_age=self._starve_age,
            outcomes=outs,
            eng=eng,
        )
        stats["quality_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        self._last_quality = stats
        return stats

    def _cold(self, ep, er, weights, pf, rf, P, T) -> np.ndarray:
        # engine phase stats (the obs plane's native layer): one dict
        # accumulates across every kernel call of this solve; timings
        # ride NEXT TO the result, never into it
        eng: Optional[dict] = {} if obs.enabled() else None
        outs: Optional[dict] = {} if obs.enabled() else None
        t0 = time.perf_counter()
        with _tracer.span("arena.candidates", cold=True, tasks=T):
            cand_p, cand_c = native.fused_topk_candidates(
                ep, er, weights, k=self.k, reverse_r=self.reverse_r,
                extra=self.extra, threads=self.threads, stats=eng,
            )
        t_gen = time.perf_counter()
        self._cand_p, self._cand_c = cand_p, cand_c
        with _tracer.span("arena.engine", engine=self.engine, cold=True):
            if self.engine == "sinkhorn":
                self._f = self._g = None
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=False, eng=eng, outs=outs
                )
            else:
                p4t, price, retired = native.auction_sparse_mt(
                    cand_p, cand_c, num_providers=P,
                    eps_start=self.eps_start, eps_end=self.eps_end,
                    threads=self.threads, stats=eng, outcomes=outs,
                )
        t_solve = time.perf_counter()
        self._p_fields, self._r_fields = pf, rf
        self._weights_key = self._wkey(weights)
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves = 0
        self._dual_age = 0
        # a cold solve starts the starvation clock fresh (everything was
        # re-seated from scratch); churn vs a pre-cold plan is undefined
        self._starve_age = None
        qual = (
            self._quality_pass(rf, p4t, price, None, outs, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            **qual,
            "cold": True,
            "engine": self.engine,
            "rows": T,
            "dirty_providers": P,
            "dirty_tasks": T,
            "changed_rows": T,
            "warm_solves_since_cold": 0,
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t0) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **(self._sink_stats if self.engine == "sinkhorn" else {}),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    def _merge_delta(
        self,
        rows: np.ndarray,
        dirty_p_idx: np.ndarray,
        delta_p: np.ndarray,
        delta_c: np.ndarray,
    ) -> np.ndarray:
        """For the task rows in ``rows``: drop dirty providers from the
        cached row, fold the delta pass's candidates (forward + reverse,
        global ids) back in by current cost, and return
        ``(changed, touched)`` masks aligned with ``rows`` (``touched``
        feeds the auction's repair_mask; ``changed`` clears retirement). Rows recomputed this solve are excluded
        by the caller — re-merging them would duplicate dirty providers
        inside one candidate list (a dup makes v1 == v2 in the bid math)."""
        cand_p = self._cand_p[rows]
        cand_c = self._cand_c[rows]
        in_dirty = np.zeros(self._price.shape[0], bool)
        in_dirty[dirty_p_idx] = True
        stale = (cand_p >= 0) & in_dirty[np.maximum(cand_p, 0)]
        dp = delta_p[rows]
        dc = delta_c[rows]
        # only rows that TOUCH a dirty provider (hold one in the cached
        # list, or receive one from the delta pass) can change: merge and
        # compare just those — at 1% churn that is a few percent of T,
        # not all of it
        touch = stale.any(axis=1) | (dp >= 0).any(axis=1)
        changed = np.zeros(rows.size, bool)
        t_idx = np.flatnonzero(touch)
        if t_idx.size == 0:
            return changed, touch
        cand_p_t = cand_p[t_idx]
        cand_c_t = cand_c[t_idx]
        stale_t = stale[t_idx]
        masked_p = np.where(stale_t, -1, cand_p_t)

        allp = np.concatenate([masked_p, dp[t_idx]], axis=1)
        allc = np.concatenate([cand_c_t, dc[t_idx]], axis=1)
        key = np.where(allp >= 0, allc, np.inf)
        k_eff = cand_p.shape[1]
        idx = np.argsort(key, axis=1, kind="stable")[:, :k_eff]
        new_p = np.take_along_axis(allp, idx, axis=1).astype(np.int32)
        new_c = np.take_along_axis(allc, idx, axis=1).astype(np.float32)
        new_c[new_p < 0] = 0.0

        # Change detection is ORDER-INSENSITIVE. The merge's argsort
        # reshuffles positions even when a row's candidate content is
        # untouched (reverse-edge extras are appended unsorted, so the
        # first merge re-sorts every row); a position-wise compare
        # cleared ~100% of the retirement carry at 16k under 1% price
        # churn and the warm auction degenerated to cold-solve work.
        # What can make a retired task viable again is exactly: (a) a
        # dirty provider ENTERING or moving within its candidate set
        # (dirty membership differs), or (b) a kept candidate getting
        # materially CHEAPER (aligned compare after sorting both lists by
        # provider id). Pure cost increases and pure losses cannot
        # un-retire; the 0.05 floor matches the CandidateCache's
        # stale_abs_tol ("drift big enough to matter").
        big = np.int32(np.iinfo(np.int32).max)
        old_dirty = np.where(stale_t, cand_p_t, big)
        new_dirty = np.where(
            (new_p >= 0) & in_dirty[np.maximum(new_p, 0)], new_p, big
        )
        old_dirty.sort(axis=1)
        new_dirty.sort(axis=1)
        member_changed = (old_dirty != new_dirty).any(axis=1)
        # when dirty membership is unchanged the full membership is too
        # (non-dirty entries only ever leave by being displaced by an
        # entering dirty one), so the id-sorted aligned compare is exact
        o_ord = np.lexsort((cand_c_t, cand_p_t), axis=1)
        n_ord = np.lexsort((new_c, new_p), axis=1)
        op = np.take_along_axis(cand_p_t, o_ord, axis=1)
        oc = np.take_along_axis(cand_c_t, o_ord, axis=1)
        npp = np.take_along_axis(new_p, n_ord, axis=1)
        ncc = np.take_along_axis(new_c, n_ord, axis=1)
        # op >= 0: empty slots carry sentinel costs (kInfeasible on fresh
        # rows, 0.0 after a merge rewrite) — without the guard a -1==-1
        # alignment reads as a 1e9 price drop and spuriously un-retires
        # every touched row on its first merge
        cheaper = (
            (op == npp) & (op >= 0) & ((oc - ncc) > 0.05)
        ).any(axis=1)

        self._cand_p[rows[t_idx]] = new_p
        self._cand_c[rows[t_idx]] = new_c
        changed[t_idx] = member_changed | cheaper
        return changed, touch

    # ---------------- the solve ----------------

    def solve(self, ep, er, weights) -> np.ndarray:
        """One marketplace solve. ``ep``/``er`` are EncodedProviders /
        EncodedRequirements (numpy- or jax-backed); returns
        provider_for_task [T] i32. ``last_stats`` reports what was
        recomputed (plus, with the obs plane on, ``gen_ms``/``solve_ms``
        stage walls and flattened ``eng_*`` native engine phase stats —
        bidding rounds, eviction counts, per-phase ns — which ride
        OUTCOME frames and the obs report).

        Dirty detection compares against the arrays of the PREVIOUS call,
        which the arena holds by reference (copying every feature column
        per solve would cost ~150 MB/solve at 1M rows): callers must pass
        freshly-built or copied arrays rather than mutating the previous
        call's buffers in place (the matcher re-encodes per solve, and
        jax-backed arrays are immutable, so both production paths are
        safe by construction)."""
        with _tracer.span("arena.solve", engine=self.engine):
            return self._solve_impl(ep, er, weights)

    def _solve_impl(self, ep, er, weights) -> np.ndarray:
        pf = _canon(ep, _P_SPEC)
        rf = _canon(er, _R_SPEC)
        P = pf["gpu_count"].shape[0]
        T = rf["cpu_cores"].shape[0]
        if P == 0 or T == 0:
            self.last_stats = {"cold": True, "assigned": 0}
            return np.full(T, -1, np.int32)

        if (
            not self._shapes_compatible(pf, rf)
            # every carried cost and selection was computed under the old
            # weights: a weight change invalidates the whole structure
            or self._weights_key != self._wkey(weights)
            or self._warm_solves >= self.cold_every
        ):
            return self._cold(ep, er, weights, pf, rf, P, T)

        dirty_p = _dirty_rows(pf, self._p_fields, _P_SPEC)
        dirty_t = _dirty_rows(rf, self._r_fields, _R_SPEC)
        # split provider churn by WHAT changed: price/load-only drift
        # ("base churn" — the per-heartbeat common case) shifts a
        # provider's whole cost column uniformly (cost = base + static,
        # ops/cost.py invariant), so every cached candidate entry can be
        # updated IN PLACE with one gather-add — no delta pass, no merge,
        # no membership change. Only structural churn (specs, location,
        # validity) needs the [dirty-P x T] regeneration. Base drift does
        # leave candidate SELECTION stale (a repriced provider keeps its
        # old edges); cold_every bounds that, same as the CandidateCache's
        # periodic re-ground.
        struct_dirty_p = _dirty_rows(
            pf, self._p_fields,
            [s for s in _P_SPEC if s[0] not in ("price", "load")],
        )
        base_only = dirty_p & ~struct_dirty_p
        n_dp, n_dt = int(struct_dirty_p.sum()), int(dirty_t.sum())
        n_base = int(base_only.sum())
        if (n_dp + n_dt) / (P + T) > self.max_dirty_frac:
            return self._cold(ep, er, weights, pf, rf, P, T)
        if n_dp == 0 and n_dt == 0 and n_base == 0:
            # byte-identical marketplace: the carried matching IS the
            # solve (prices/retirement already consistent with it)
            self._warm_solves += 1
            qual: dict = {}
            if obs.enabled():
                # nothing changed, so the carried gap/outcome
                # certificate is still exact — reuse it instead of
                # re-scanning [T x K]; only the tick-indexed signals
                # (starvation ages, zero churn) advance
                t_q = time.perf_counter()
                self._starve_age = _quality.starvation_update(
                    self._starve_age, self._p4t,
                    rf["valid"].astype(bool),
                )
                qual = dict(self._last_quality)
                qual["churn_rows"] = 0
                qual["churn_ratio"] = 0.0
                qual["starve_max"] = (
                    int(self._starve_age.max())
                    if self._starve_age.size else 0
                )
                qual["starving"] = int((self._starve_age > 0).sum())
                qual["starve_hist"] = _quality.starvation_hist(
                    self._starve_age
                )
                qual["quality_ms"] = round(
                    (time.perf_counter() - t_q) * 1e3, 3
                )
                self._last_quality = qual
            self.last_stats = {
                **qual,
                "cold": False,
                "rows": T,
                "dirty_providers": 0,
                "dirty_tasks": 0,
                "changed_rows": 0,
                "warm_solves_since_cold": self._warm_solves,
                "assigned": int((self._p4t >= 0).sum()),
            }
            return self._p4t.copy()

        eng: Optional[dict] = {} if obs.enabled() else None
        outs: Optional[dict] = {} if obs.enabled() else None
        # the previous tick's plan, captured BEFORE the dirty-task
        # re-seat below mutates it in place — the churn ratio compares
        # plan-to-plan, not plan-to-scratchpad
        prev_p4t = self._p4t.copy() if obs.enabled() else None
        t_start = time.perf_counter()
        old_price = self._p_fields["price"]
        old_load = self._p_fields["load"]
        self._p_fields, self._r_fields = pf, rf
        changed = dirty_t.copy()
        # rows whose candidate COSTS move this solve, in either direction:
        # the only rows whose eps-CS happiness can degrade (prices are
        # monotone), so the only rows the warm repair needs to scan
        repair = dirty_t.copy()

        # ---- base-only drift: shift cached costs in place (one gather)
        if n_base:
            db = np.zeros(P, np.float32)
            b_idx = np.flatnonzero(base_only)
            db[b_idx] = (
                np.float32(weights.price) * (pf["price"][b_idx] - old_price[b_idx])
                + np.float32(weights.load) * (pf["load"][b_idx] - old_load[b_idx])
            )
            cp_safe = np.maximum(self._cand_p, 0)
            entry_db = np.where(self._cand_p >= 0, db[cp_safe], 0.0)
            self._cand_c += entry_db
            repair |= (entry_db != 0.0).any(axis=1)
            # a provider that got materially CHEAPER can un-retire every
            # task holding it as a candidate; pricier/flat drift cannot
            cheap = db < -0.05
            changed |= (
                (self._cand_p >= 0) & cheap[cp_safe]
            ).any(axis=1)

        # ---- dirty tasks: fresh fused pass against the full fleet
        if n_dt:
            t_idx = np.flatnonzero(dirty_t)
            sub_er = _subset(rf, t_idx, _R_SPEC)
            tp, tc = native.fused_topk_candidates(
                _as_ns(pf, _P_SPEC), sub_er, weights, k=self.k,
                reverse_r=self.reverse_r, extra=self.extra,
                threads=self.threads, stats=eng,
            )
            self._cand_p[t_idx] = tp
            self._cand_c[t_idx] = tc
            # a dirty task's seat predates its new requirement: re-seat
            # from scratch (the warm repair would keep a stale-but-eps-OK
            # seat on candidates the task no longer declares)
            self._p4t[t_idx] = -1

        # ---- dirty providers: one [dirty-P x T] delta pass, merged into
        # every row NOT already recomputed above
        if n_dp:
            p_idx = np.flatnonzero(struct_dirty_p)
            sub_ep = _subset(pf, p_idx, _P_SPEC)
            kd = min(self.k, n_dp)
            dp_local, dc = native.fused_topk_candidates(
                sub_ep, _as_ns(rf, _R_SPEC), weights, k=kd,
                reverse_r=self.reverse_r, extra=self.extra,
                threads=self.threads, stats=eng,
            )
            # local -> global provider ids
            dp = np.where(
                dp_local >= 0, p_idx[np.maximum(dp_local, 0)], -1
            ).astype(np.int32)
            keep_rows = np.flatnonzero(~dirty_t)
            if keep_rows.size:
                merge_changed, merge_touched = self._merge_delta(
                    keep_rows, p_idx, dp, dc
                )
                changed[keep_rows] |= merge_changed
                repair[keep_rows] |= merge_touched

        # ---- feasibility guard: a seat whose provider left the row's
        # candidate list (struct churn dropped it, or an entering cheaper
        # provider displaced it in the merge) must be unseated HERE, not
        # left to the auction's eps-CS repair — with max_release capping
        # the repair, an over-cap infeasible seat would persist and then
        # be skipped by later repair masks (its row no longer churns).
        # Only rows whose lists moved this solve (repair mask) can have
        # lost their seat; base-only drift never changes membership.
        seat_check = np.flatnonzero(repair & (self._p4t >= 0))
        if seat_check.size:
            in_list = (
                self._cand_p[seat_check]
                == self._p4t[seat_check, None]
            ).any(axis=1)
            lost = seat_check[~in_list]
            if lost.size:
                self._p4t[lost] = -1
                changed[lost] = True  # unseated: must be free to re-bid

        t_gen = time.perf_counter()
        _tracer.record_span(
            "arena.candidates", int(t_start * 1e9),
            int((t_gen - t_start) * 1e9), cold=False,
            dirty_providers=n_dp, dirty_tasks=n_dt, base_only=n_base,
        )
        # ---- solve over the (updated) cached candidate structure:
        # warm dual carry on most ticks, a full dual refresh on schedule
        dual_refresh = (
            self.dual_refresh_every > 0
            and self._dual_age >= self.dual_refresh_every
        )
        if self.engine == "sinkhorn":
            # entropic potentials re-converge from the carried (f, g) —
            # the dual refresh re-grounds only the REFEREE's retirement/
            # seeding (the cardinality-bleed half), never the potentials:
            # sinkhorn duals are a fixed point recomputed in full every
            # solve, so they cannot ratchet the way auction prices do
            if dual_refresh:
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=True, eng=eng, outs=outs
                )
                self._dual_age = 0
            else:
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=True,
                    retired=self._retired & ~changed,
                    seed=self._p4t,
                    max_release=self.max_release,
                    eng=eng, outs=outs,
                )
                self._dual_age += 1
        elif dual_refresh:
            p4t, price, retired = native.auction_sparse_mt(
                self._cand_p, self._cand_c, num_providers=P,
                eps_start=self.eps_start, eps_end=self.eps_end,
                threads=self.threads, stats=eng, outcomes=outs,
            )
            self._dual_age = 0
        else:
            retired = self._retired & ~changed
            p4t, price, retired = native.auction_sparse_mt(
                self._cand_p, self._cand_c, num_providers=P,
                eps_start=max(self.warm_eps_start, self.eps_end),
                eps_end=self.eps_end,
                threads=self.threads,
                price=self._price, retired=retired,
                seed_provider_for_task=self._p4t,
                max_release=self.max_release,
                repair_mask=repair,
                stats=eng, outcomes=outs,
            )
            self._dual_age += 1
        t_solve = time.perf_counter()
        _tracer.record_span(
            "arena.engine", int(t_gen * 1e9),
            int((t_solve - t_gen) * 1e9), engine=self.engine, cold=False,
        )
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves += 1
        qual = (
            self._quality_pass(rf, p4t, price, prev_p4t, outs, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            **qual,
            "cold": False,
            "engine": self.engine,
            "rows": T,
            "dual_refresh": dual_refresh,
            "dirty_providers": n_dp,
            "base_only_providers": n_base,
            "dirty_tasks": n_dt,
            "changed_rows": int(changed.sum()),
            "warm_solves_since_cold": self._warm_solves,
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t_start) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **(self._sink_stats if self.engine == "sinkhorn" else {}),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t
