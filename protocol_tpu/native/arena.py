"""Persistent warm-solve arena for the native CPU engine (engine=native-mt).

The degraded-mode twin of the CandidateCache + warm-kernel pipeline
(sched/cand_cache.py + ops/sparse.assign_auction_sparse_warm): repeated
solves against an incrementally-churned marketplace reuse everything that
survives between ticks instead of rebuilding it —

  - **Candidate structure.** The fused cost+top-k pass is the dominant
    stage (~90% of a cold native solve). The arena keeps the assembled
    [T, k+extra] bidirectional candidate lists PLUS the per-provider
    reverse-edge keys as one persistent, incrementally-REPAIRED object:
    on churn, ``native.repair_topk_candidates`` rewrites only the
    rows/columns the dirty provider/task sets reach and the result is
    BIT-IDENTICAL to a from-scratch rebuild on the current features —
    the structure is exact at every tick, never a drifting cache. A
    1%-churn tick issues zero full-matrix candidate passes
    (``last_stats["cand_cold_passes"] == 0``); cold builds route through
    the capability-bucket pruner (sub-quadratic when GPU constraints are
    selective, per-row full-scan fallback otherwise — also exact).
  - **Auction dual state.** Prices per provider, the retirement mask per
    task, and the previous matching are carried into a single-phase warm
    auction (native.auction_sparse_mt), whose eps-CS repair evicts stale
    seeds. Retirement flags are cleared for exactly the rows the repair
    reports ``changed`` (membership moved, or a kept candidate got
    materially cheaper) — the same caller contract the JAX warm kernel
    documents ("rows whose costs or candidates changed must be cleared").

Dirty detection is value-based: each provider/requirement feature column
is compared row-wise against the previous solve's columns, so any change
that can affect feasibility or cost (specs, price, load, validity, the
requirement DSL fields) marks its row dirty and ONLY that row's reach is
repaired. Price/load drift is churn like any other (the exactness
contract re-scores the drifted columns; the historical in-place cost
shift kept membership stale between cold re-grounds). Backstops: a
dirty fraction above ``max_dirty_frac`` triggers a full rebuild (the
repair would cost more than it saves), and ``cold_every`` re-grounds the
auction duals (the structure itself no longer drifts — repair is exact —
so the cadence only bounds the warm chain's monotone price ratchet).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from protocol_tpu import native, obs
from protocol_tpu.obs import quality as _quality
from protocol_tpu.obs.spans import TRACER as _tracer

# canonical dtypes per encoded field (mirrors native.fused_topk_candidates'
# coercions so comparing cached vs incoming columns is exact)
_P_SPEC = (
    ("gpu_count", np.int32), ("gpu_mem_mb", np.int32),
    ("gpu_model_id", np.int32), ("has_gpu", np.uint8),
    ("has_cpu", np.uint8), ("cpu_cores", np.int32), ("ram_mb", np.int32),
    ("storage_gb", np.int32), ("lat", np.float32), ("lon", np.float32),
    ("has_location", np.uint8), ("price", np.float32), ("load", np.float32),
    ("valid", np.uint8),
)
_R_SPEC = (
    ("cpu_required", np.uint8), ("cpu_cores", np.int32), ("ram_mb", np.int32),
    ("storage_gb", np.int32), ("gpu_opt_valid", np.uint8),
    ("gpu_count", np.int32), ("gpu_mem_min", np.int32),
    ("gpu_mem_max", np.int32), ("gpu_total_mem_min", np.int32),
    ("gpu_total_mem_max", np.int32), ("gpu_model_mask", np.uint32),
    ("gpu_model_constrained", np.uint8), ("lat", np.float32),
    ("lon", np.float32), ("has_location", np.uint8),
    ("priority", np.float32), ("valid", np.uint8),
)


# persisted candidate-structure dtypes: these arrays ride checkpoint
# journal frames (faults/checkpoint.py) and migration handoffs, so their
# widths are a durable on-disk contract — the dtype-contract lint
# cross-checks this table against export_state's cand_* keys, and
# restore_state coerces through it, so a drifted width can neither land
# silently nor reinterpret an archived checkpoint's raw bytes
_CAND_STATE_DTYPES = {
    "cand_p": np.int32,
    "cand_c": np.float32,
    "cand_rev": np.uint64,
    "cand_slack_p": np.int32,
    "cand_slack_c": np.float32,
}


def _canon(enc, spec) -> dict[str, np.ndarray]:
    return {
        name: np.ascontiguousarray(np.asarray(getattr(enc, name)), dtype)
        for name, dtype in spec
    }


def _dirty_rows(new: dict, old: dict, spec) -> np.ndarray:
    """Row-wise OR of per-field inequality (trailing axes collapsed)."""
    n = new[spec[0][0]].shape[0]
    dirty = np.zeros(n, bool)
    for name, _ in spec:
        diff = new[name] != old[name]
        dirty |= diff.reshape(n, -1).any(axis=1)
    return dirty


def _as_ns(fields: dict, spec) -> object:
    ns = type("_Full", (), {})()
    for name, _ in spec:
        setattr(ns, name, fields[name])
    return ns


class NativeSolveArena:
    def __init__(
        self,
        k: int = 64,
        reverse_r: int = 8,
        extra: int = 16,
        threads: int = 0,
        cold_every: int = 256,
        max_dirty_frac: float = 0.25,
        eps_start: float = 4.0,
        eps_end: float = 0.02,
        max_release: int = 64,
        dual_refresh_every: int = 16,
        warm_eps_start: float = 0.32,
        engine: str = "auction",
        sink_eps_start: float = 1.0,
        sink_eps_end: float = 0.05,
        sink_scale: float = 0.25,
        sink_iters: int = 50,
        # marginal-drift tolerance: the rounding referee consumes the
        # plan's ARGMAX structure, which stabilizes one to two orders
        # before the marginals polish — 1e-2 halves the iteration bill
        # with no measured effect on the rounded matching
        sink_tol: float = 1e-2,
        bucketed: bool = True,
        coverage_frac: float = 0.6,
        slack: int = 16,
        event_max_bids: int = 16384,
    ):
        if engine not in ("auction", "sinkhorn"):
            raise ValueError(
                f"engine must be auction|sinkhorn, got {engine!r}"
            )
        self.k = k
        self.reverse_r = reverse_r
        self.extra = extra
        self.threads = threads
        # capability-bucket pruner for cold builds + repair rescans:
        # bit-identical output (provably-infeasible pruning + coverage
        # fallback), so the knob is purely a work/latency trade
        self.bucketed = bucketed
        self.coverage_frac = coverage_frac
        # per-row next-cheapest shadow beyond the top-k: the repair
        # kernel's deletion absorber (a churned-out top-k member is
        # replaced from the slack instead of forcing a row re-score);
        # lazily degraded, re-armed by rescans/cold builds, never part
        # of the auction-visible structure
        self.slack = slack
        self.cold_every = cold_every
        self.max_dirty_frac = max_dirty_frac
        self.eps_start = eps_start
        self.eps_end = eps_end
        # Solve engine over the (shared) candidate structure:
        #   "auction"   the eps-scaled Jacobi auction with full dual carry
        #               (prices + retirement + matching) — the PR-1 path.
        #   "sinkhorn"  sparse entropic OT (native.sinkhorn_sparse_mt):
        #               O(nnz) log-domain potentials annealed over an eps
        #               ladder, warm (f, g) carry across churn (uniform-
        #               shift invariant, so carried potentials are sound),
        #               then INJECTIVE rounding by the sparse auction as
        #               referee — seeded with price = max(f) - f, so the
        #               referee starts from the entropic solution's global
        #               prices and converges in a handful of rounds.
        self.engine = engine
        self.sink_eps_start = sink_eps_start
        self.sink_eps_end = sink_eps_end
        self.sink_scale = sink_scale
        self.sink_iters = sink_iters
        self.sink_tol = sink_tol
        # warm-solve eviction cap (native.auction_sparse_mt max_release):
        # bounds the per-solve re-bidding wave under drift; re-ranked every
        # solve so staleness is amortized, and cold_every re-grounds fully
        self.max_release = max_release
        # Dual refresh: the warm chain's price ratchet is monotone, so
        # war losers retire and STAY retired while idle providers
        # accumulate — measured ~14 lost assignments per tick at 16k
        # under 1% churn, with no plateau. Every ``dual_refresh_every``
        # warm solves the auction re-runs with fresh prices/retirement
        # over the CACHED candidate structure (the expensive part is
        # kept): cardinality snaps back to the cold solve's level and the
        # amortized cost is a few tens of ms per tick. cold_every still
        # re-grounds the structure itself.
        self.dual_refresh_every = dual_refresh_every
        # Per-event auction WORK BUDGET (apply_rows only): a single
        # event in a saturated pocket can trigger a give-up war —
        # displaced tasks ratcheting prices to the give-up floor over
        # hundreds of thousands of fine-eps bids whose outcome
        # (retirement) is already decided. The budget bounds one
        # event's bid loop; the unconverged tasks stay unassigned (not
        # retired) and the NEXT event's call resumes the war from the
        # carried prices — per-event latency is bounded and the war
        # amortizes, while reconciliation periodically re-grounds with
        # an unbudgeted full solve. 0 = unbounded (the historical
        # behavior).
        self.event_max_bids = int(event_max_bids)
        # Warm solves open at a COARSE eps and scale down (0.32 -> 0.08 ->
        # eps_end by the engine's 0.25 scale): evicted seats separate from
        # rivals in a handful of coarse rounds instead of thousands of
        # eps_end-increment bidding-war rounds. Measured at 16k/1% churn:
        # 182 -> 107 ms mean tick at a ~1 point cardinality-floor cost
        # (the dual refresh re-grounds the floor every cycle). Set to
        # eps_end for the historical single-fine-phase behavior.
        self.warm_eps_start = warm_eps_start
        self.last_stats: dict = {}
        self.invalidate()

    @property
    def price(self) -> Optional[np.ndarray]:
        """Carried auction prices [P] after the last solve (dual state)."""
        return self._price

    @property
    def retired(self) -> Optional[np.ndarray]:
        """Carried retirement mask [T] after the last solve."""
        return self._retired

    @property
    def potentials(self) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Carried Sinkhorn dual potentials (f [P], g [T]) after the last
        solve — (None, None) on the auction engine / before any solve."""
        return self._f, self._g

    def export_state(self) -> Optional[dict]:
        """The carried warm state as a flat dict of scalars and arrays —
        everything the next solve's trajectory depends on: the candidate
        structure (path-dependent: incremental merges reorder lists, so
        regenerating it cold would NOT reproduce the warm chain), the
        auction/sinkhorn duals, the previous matching, the shadow
        columns' role is played by the caller (who must restore the same
        columns), and the cadence cursors (``warm_solves`` drives
        ``cold_every``, ``dual_age`` drives ``dual_refresh_every`` — a
        restore that dropped them would re-ground on a different tick).

        Returns None before any solve (nothing carried: a restore would
        just be a cold arena). Arrays are copies — a checkpoint must not
        alias live solver state."""
        if self._cand_p is None:
            return None

        def _c(a):
            return None if a is None else np.array(a, copy=True)

        out = {
            "cand_p": _c(self._cand_p),
            "cand_c": _c(self._cand_c),
            "cand_rev": _c(self._rev),
            "cand_slack_p": _c(self._slack_p),
            "cand_slack_c": _c(self._slack_c),
            "price": _c(self._price),
            "retired": _c(self._retired),
            "p4t": _c(self._p4t),
            "f": _c(self._f),
            "g": _c(self._g),
            "starve_age": _c(self._starve_age),
            "warm_solves": int(self._warm_solves),
            "dual_age": int(self._dual_age),
            "weights_key": tuple(self._weights_key),
            # float-pipeline provenance: the candidate structure's costs
            # were scored under this ISA — a restore under a different
            # one cannot be repaired bit-exactly (see restore_state)
            "native_isa": native.current_isa(),
        }
        # the arena's OWN dirty-detection baseline (it can lag the
        # session's current columns when degraded ticks applied deltas
        # without solving): restoring the session columns as the
        # baseline would silently swallow that accumulated churn
        for name, _ in _P_SPEC:
            out[f"pf_{name}"] = _c(self._p_fields[name])
        for name, _ in _R_SPEC:
            out[f"rf_{name}"] = _c(self._r_fields[name])
        return out

    def restore_state(self, ep, er, state: dict) -> None:
        """Rehydrate the warm chain from :meth:`export_state` output plus
        the exact columns (``ep``/``er``) the exporting arena last
        solved. The next ``solve`` continues the chain bit-identically:
        dirty detection diffs against these columns, the candidate
        structure and duals are the exported ones, and the cadence
        cursors resume mid-schedule. The arena's construction params
        (k / eps ladder / engine / refresh cadences) must match the
        exporter's — the checkpoint layer persists and re-applies them."""
        self.invalidate()
        if "pf_gpu_count" in state:
            # exported baseline columns win (see export_state: they can
            # lag the caller's current columns after degraded ticks)
            self._p_fields = {
                name: np.array(state[f"pf_{name}"], copy=True)
                for name, _ in _P_SPEC
            }
            self._r_fields = {
                name: np.array(state[f"rf_{name}"], copy=True)
                for name, _ in _R_SPEC
            }
        else:
            self._p_fields = _canon(ep, _P_SPEC)
            self._r_fields = _canon(er, _R_SPEC)
        self._cand_p = np.array(
            state["cand_p"], _CAND_STATE_DTYPES["cand_p"], copy=True
        )
        self._cand_c = np.array(
            state["cand_c"], _CAND_STATE_DTYPES["cand_c"], copy=True
        )
        rev = state.get("cand_rev")
        # pre-repair checkpoints carry no reverse-edge keys, and a
        # config-skewed carry (exporter built the structure at a
        # different reverse_r / candidate width than this arena runs)
        # cannot be repaired against this arena's knobs: both degrade to
        # an honest cold re-ground on the first solve instead of a hard
        # shape error mid-tick (warm duals would be unsound against a
        # regenerated structure anyway)
        n_p = self._p_fields["gpu_count"].shape[0]
        n_t = self._r_fields["cpu_cores"].shape[0]
        # ISA-skewed carry: the exported costs came from a different
        # float pipeline than this process runs, so repairing against
        # them would break the bit-identical-to-rebuild promise — same
        # honest cold re-ground as a config skew. Pre-ISA checkpoints
        # (no tag) were scored by the historical scalar pipeline.
        exported_isa = state.get("native_isa", "scalar")
        if (
            rev is None
            or exported_isa != native.current_isa()
            or np.asarray(rev).shape != (n_p, self.reverse_r)
            or self._cand_p.ndim != 2
            or self._cand_p.shape
            != (n_t, min(self.k, n_p) + self.extra)
        ):
            self.invalidate()
            return
        self._rev = np.array(
            rev, _CAND_STATE_DTYPES["cand_rev"], copy=True
        )
        sp, sc = state.get("cand_slack_p"), state.get("cand_slack_c")
        # slack is an optimization, not a correctness input: a carry
        # without it repairs correctly, just with more row re-scores —
        # but a HALF-present or shape-skewed pair is dropped whole (the
        # repair wrapper would otherwise raise mid-tick on the first
        # warm solve instead of just re-scoring more rows)
        if (
            sp is None or sc is None
            or np.asarray(sp).ndim != 2
            or np.asarray(sp).shape[0] != n_t
            or np.asarray(sc).shape != np.asarray(sp).shape
        ):
            sp = sc = None
        self._slack_p = None if sp is None else np.array(
            sp, _CAND_STATE_DTYPES["cand_slack_p"], copy=True
        )
        self._slack_c = None if sc is None else np.array(
            sc, _CAND_STATE_DTYPES["cand_slack_c"], copy=True
        )
        for name in ("price", "retired", "p4t", "f", "g", "starve_age"):
            v = state.get(name)
            setattr(
                self, f"_{name}",
                None if v is None else np.array(v, copy=True),
            )
        self._warm_solves = int(state["warm_solves"])
        self._dual_age = int(state["dual_age"])
        self._weights_key = tuple(state["weights_key"])

    def invalidate(self) -> None:
        """Drop all carried state: the next solve is cold."""
        self._p_fields: Optional[dict] = None
        self._r_fields: Optional[dict] = None
        self._weights_key: Optional[tuple] = None
        self._cand_p: Optional[np.ndarray] = None
        self._cand_c: Optional[np.ndarray] = None
        self._rev: Optional[np.ndarray] = None  # [P, reverse_r] u64 keys
        self._slack_p: Optional[np.ndarray] = None  # [T, slack] shadow
        self._slack_c: Optional[np.ndarray] = None
        self._price: Optional[np.ndarray] = None
        self._retired: Optional[np.ndarray] = None
        self._p4t: Optional[np.ndarray] = None
        self._f: Optional[np.ndarray] = None  # sinkhorn provider duals
        self._g: Optional[np.ndarray] = None  # sinkhorn task duals
        self._sink_stats: dict = {}
        self._warm_solves = 0
        self._dual_age = 0
        # quality plane (obs): per-task consecutive-unassigned ages and
        # the last computed quality scalars (reused verbatim by the
        # byte-identical short-circuit tick — nothing changed, so the
        # gap/outcome certificate is still exact)
        self._starve_age: Optional[np.ndarray] = None
        self._last_quality: dict = {}
        # stream plane: the last apply_rows call's touched-row mask
        self.last_repair_mask: Optional[np.ndarray] = None
        # columns apply_rows has privatized since the baseline was last
        # (re)assigned: solve() holds caller arrays by REFERENCE (and
        # trace-decoded columns are read-only frombuffer views), so the
        # first in-place event write to a column must copy it — after
        # that the arena owns it and writes are O(rows)
        self._owned_cols: set = set()

    # ---------------- internals ----------------

    @staticmethod
    def _wkey(weights) -> tuple:
        return (
            float(weights.price), float(weights.load),
            float(weights.proximity), float(weights.priority),
        )

    def _shapes_compatible(self, pf: dict, rf: dict) -> bool:
        old_p, old_r = self._p_fields, self._r_fields
        if old_p is None or old_r is None:
            return False
        return all(
            pf[n].shape == old_p[n].shape for n, _ in _P_SPEC
        ) and all(rf[n].shape == old_r[n].shape for n, _ in _R_SPEC)

    def _sinkhorn_round(
        self,
        P: int,
        warm: bool,
        retired: Optional[np.ndarray] = None,
        seed: Optional[np.ndarray] = None,
        max_release: int = 0,
        eng: Optional[dict] = None,
        outs: Optional[dict] = None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The sinkhorn engine's solve stage over the CURRENT cached
        candidate structure: entropic potentials (cold: the full anneal
        ladder from zero duals; warm: one fine-eps phase from the carried
        (f, g) — churn only perturbs the fixed point, so a handful of
        O(nnz) iterations re-converge it), then injective rounding by the
        sparse auction referee seeded with price = max(f) - f. The uniform
        downshift keeps referee prices nonnegative and far from the
        give-up floor without changing a single price DIFFERENCE — the
        same soundness argument as the warm auction's price downshift.

        The referee's eps-CS repair runs over ALL rows (repair_mask=None):
        unlike the auction engine's carried prices, referee prices are
        re-derived from the (globally shifted) potentials each solve, so
        "only churned rows can have degraded" does not hold; the full
        [T x K] repair scan is one pass over the candidate structure —
        noise next to the potential iterations. ``max_release`` still caps
        the eviction wave.
        """
        phase_stats: list = []
        carried = (
            warm
            and self._f is not None
            and self._f.shape[0] == P
            and self._g is not None
            and self._g.shape[0] == self._cand_p.shape[0]
        )
        if carried:
            f, g, iters, err = native.sinkhorn_sparse_mt(
                self._cand_p, self._cand_c, P,
                eps=self.sink_eps_end, max_iters=self.sink_iters,
                tol=self.sink_tol, threads=self.threads,
                f=self._f, g=self._g, stats=eng,
            )
            phase_stats.append({
                "eps": self.sink_eps_end, "iters": iters,
                "err": round(err, 6), "warm": True,
            })
        else:
            f, g = native.sinkhorn_sparse_anneal(
                self._cand_p, self._cand_c, P,
                eps_start=self.sink_eps_start, eps_end=self.sink_eps_end,
                scale=self.sink_scale, iters_per_phase=self.sink_iters,
                tol=self.sink_tol, threads=self.threads,
                phase_stats=phase_stats, stats=eng,
            )
        self._f, self._g = f, g
        self._sink_stats = {
            "sinkhorn_phases": len(phase_stats),
            "sinkhorn_iters": int(sum(s["iters"] for s in phase_stats)),
            "sinkhorn_err": phase_stats[-1]["err"] if phase_stats else None,
        }
        # Referee seed prices from the provider duals — downshifted and
        # capped below the give-up floor; the formula and its soundness
        # argument live in native.sinkhorn_referee_prices (the one home
        # shared with the perf gate, stage-S script, and bench)
        price0 = native.sinkhorn_referee_prices(
            f, self._cand_p, self._cand_c
        )
        return native.auction_sparse_mt(
            self._cand_p, self._cand_c, num_providers=P,
            eps_start=max(self.warm_eps_start, self.eps_end),
            eps_end=self.eps_end,
            threads=self.threads,
            price=price0, retired=retired,
            seed_provider_for_task=seed, max_release=max_release,
            stats=eng, outcomes=outs,
        )

    def _quality_pass(
        self,
        rf: dict,
        p4t: np.ndarray,
        price: Optional[np.ndarray],
        prev_p4t: Optional[np.ndarray],
        outs: Optional[dict],
        eng: Optional[dict] = None,
    ) -> dict:
        """The decision-quality record for one solve (obs plane on):
        certified duality gap from the carried duals, plan churn vs the
        previous tick, starvation ages, and the native outcome taxonomy
        — flat scalars for ``last_stats`` (wall in ``quality_ms``).
        Timings and certificates ride NEXT TO the result, never into
        it."""
        t0 = time.perf_counter()
        stats, self._starve_age = _quality.tick_quality(
            self._cand_p, self._cand_c, p4t, price,
            valid=rf["valid"].astype(bool),
            prev_p4t=prev_p4t,
            starve_age=self._starve_age,
            outcomes=outs,
            eng=eng,
        )
        stats["quality_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        self._last_quality = stats
        return stats

    def _cold(self, ep, er, weights, pf, rf, P, T) -> np.ndarray:
        # engine phase stats (the obs plane's native layer): one dict
        # accumulates across every kernel call of this solve; timings
        # ride NEXT TO the result, never into it
        eng: Optional[dict] = {} if obs.enabled() else None
        outs: Optional[dict] = {} if obs.enabled() else None
        t0 = time.perf_counter()
        with _tracer.span("arena.candidates", cold=True, tasks=T):
            # the persistent reverse-edge keys ride along so the next
            # churn tick can REPAIR this structure instead of paying
            # another full-matrix pass
            persist = (
                self.reverse_r > 0 and self.extra > 0
                and min(self.k, P) > 0
            )
            rev = np.zeros((P, self.reverse_r), np.uint64) if persist else None
            slack = (
                (np.zeros((T, self.slack), np.int32),
                 np.zeros((T, self.slack), np.float32))
                if persist and self.slack > 0 else None
            )
            cand_p, cand_c = native.fused_topk_candidates(
                ep, er, weights, k=self.k, reverse_r=self.reverse_r,
                extra=self.extra, threads=self.threads, stats=eng,
                bucketed=self.bucketed, coverage_frac=self.coverage_frac,
                rev_out=rev, slack_out=slack,
            )
        t_gen = time.perf_counter()
        self._cand_p, self._cand_c = cand_p, cand_c
        self._rev = rev
        self._slack_p = slack[0] if slack is not None else None
        self._slack_c = slack[1] if slack is not None else None
        with _tracer.span("arena.engine", engine=self.engine, cold=True):
            if self.engine == "sinkhorn":
                self._f = self._g = None
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=False, eng=eng, outs=outs
                )
            else:
                p4t, price, retired = native.auction_sparse_mt(
                    cand_p, cand_c, num_providers=P,
                    eps_start=self.eps_start, eps_end=self.eps_end,
                    threads=self.threads, stats=eng, outcomes=outs,
                )
        t_solve = time.perf_counter()
        self._p_fields, self._r_fields = pf, rf
        self._owned_cols = set()
        self._weights_key = self._wkey(weights)
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves = 0
        self._dual_age = 0
        # a cold solve starts the starvation clock fresh (everything was
        # re-seated from scratch); churn vs a pre-cold plan is undefined
        self._starve_age = None
        qual = (
            self._quality_pass(rf, p4t, price, None, outs, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            "native_isa": native.current_isa(),
            **qual,
            "cold": True,
            "engine": self.engine,
            "rows": T,
            "cand_cold_passes": 1,
            "dirty_providers": P,
            "dirty_tasks": T,
            "changed_rows": T,
            "warm_solves_since_cold": 0,
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t0) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **(self._sink_stats if self.engine == "sinkhorn" else {}),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    # ---------------- streaming entry points ----------------

    def apply_rows(
        self,
        provider_rows: Optional[np.ndarray],
        p_rows: Optional[dict],
        task_rows: Optional[np.ndarray],
        r_rows: Optional[dict],
        weights,
        event_eps_start: Optional[float] = None,
    ) -> np.ndarray:
        """Single-event repair entry (the stream engine's hot path): the
        caller names the churned rows EXPLICITLY, so there is no O(P+T)
        value-diff pass — the cost per call is O(churned rows) repair +
        one masked warm engine pass.

        ``provider_rows``/``task_rows`` are row indices into the arena's
        current columns; ``p_rows``/``r_rows`` are full-spec column
        dicts with one value per index (the wire delta shape). Rows
        whose values equal the current columns are dropped (an event
        replay is a no-op by construction). The arena's own field
        baseline is updated IN PLACE for the truly-dirty rows, so a
        later batch ``solve`` against the same columns sees zero dirty
        rows — stream and batch entries stay one consistent state.

        Requires a primed arena (``solve`` ran at least once and the
        persistent candidate structure exists) under the SAME weights;
        raises RuntimeError/ValueError otherwise — the stream engine
        treats that as "re-prime with a batch solve", never silently
        degrades. Never issues a full-matrix candidate pass
        (``last_stats["cand_cold_passes"] == 0`` always).

        The warm engine runs a SINGLE fine-eps phase by default
        (``event_eps_start`` = ``eps_end``): a one-event perturbation
        re-seats in a handful of bids, and the coarse warm ladder's
        multi-phase overhead would dominate sub-tick latency. Returns
        provider_for_task [T] (the arena's live padded row space)."""
        if self._cand_p is None or self._rev is None:
            raise RuntimeError(
                "arena not primed for apply_rows: run solve() first "
                "(the persistent candidate structure must exist)"
            )
        if self._weights_key != self._wkey(weights):
            raise ValueError(
                "apply_rows under different weights: the carried "
                "structure was scored under the old weights (re-prime "
                "with a batch solve)"
            )
        t_start = time.perf_counter()
        P = self._p_fields["gpu_count"].shape[0]
        T = self._r_fields["cpu_cores"].shape[0]

        def _narrow(rows, vals, fields, spec, n, side):
            """Coerce event values to spec dtypes, keep only rows that
            actually change a field, and write them into the arena's
            baseline in place (privatizing a column on its first write —
            the baseline may be a caller-shared or read-only buffer).
            Returns the truly-dirty index array."""
            if rows is None or vals is None:
                return np.zeros(0, np.int32)
            rows = np.asarray(rows, np.int64).ravel()
            if rows.size == 0:
                return np.zeros(0, np.int32)
            if rows.min() < 0 or rows.max() >= n:
                raise ValueError(f"event row index out of range [0, {n})")
            dirty = np.zeros(rows.size, bool)
            canon = {}
            for name, dtype in spec:
                v = np.ascontiguousarray(np.asarray(vals[name]), dtype)
                if v.shape[0] != rows.size:
                    raise ValueError(
                        f"event column {name!r} has {v.shape[0]} rows "
                        f"for {rows.size} row indices"
                    )
                canon[name] = v
                diff = fields[name][rows] != v
                dirty |= diff.reshape(rows.size, -1).any(axis=1)
            keep = np.flatnonzero(dirty)
            if keep.size:
                idx = rows[keep]
                for name, _ in spec:
                    key = (side, name)
                    if key not in self._owned_cols:
                        fields[name] = fields[name].copy()
                        self._owned_cols.add(key)
                    fields[name][idx] = canon[name][keep]
            return rows[keep].astype(np.int32)

        # ---- dual pre-conditioning for separable (price/load) drift.
        # The cost model's provider term is separable: score(t, p) =
        # base(p) + task/cross terms, base = w_price*price + w_load*
        # load. A heartbeat that drops base(p) by d makes p a magnet:
        # every nearby task re-bids it up by fine-eps increments until
        # its dual price has risen ~d — a bidding war of d/eps rounds
        # for an outcome KNOWN in closed form. Pre-bumping price[p] by
        # d keeps c+price invariant for every row (the current plan
        # stays eps-CS instantly; the seat holder still pockets the
        # cheaper rate), prices stay monotone (the gap tracker's
        # soundness argument), and any nonnegative dual certifies — the
        # war is skipped, not hidden. Applied only to auction duals on
        # non-structural (price/load-only) churn; cost INCREASES never
        # pre-drop (monotonicity), they release via the eps-CS repair.
        bump_rows = bump_vals = None
        if (
            self.engine == "auction"
            and self._price is not None
            and provider_rows is not None and p_rows is not None
        ):
            pr = np.asarray(provider_rows, np.int64).ravel()
            if pr.size and pr.min() >= 0 and pr.max() < P:
                old_base = (
                    float(weights.price)
                    * self._p_fields["price"][pr].astype(np.float64)
                    + float(weights.load)
                    * self._p_fields["load"][pr].astype(np.float64)
                )
                structural = np.zeros(pr.size, bool)
                for name, dtype in _P_SPEC:
                    if name in ("price", "load"):
                        continue
                    v = np.ascontiguousarray(
                        np.asarray(p_rows[name]), dtype
                    )
                    if v.shape[0] != pr.size:
                        break  # shape error: _narrow raises below
                    diff = self._p_fields[name][pr] != v
                    structural |= diff.reshape(pr.size, -1).any(axis=1)
                else:
                    new_base = (
                        float(weights.price) * np.asarray(
                            p_rows["price"], np.float64
                        )
                        + float(weights.load) * np.asarray(
                            p_rows["load"], np.float64
                        )
                    )
                    dbase = new_base - old_base
                    sel = ~structural & (dbase < 0)
                    if sel.any():
                        bump_rows = pr[sel]
                        bump_vals = (-dbase[sel]).astype(np.float32)
        dirty_p = _narrow(
            provider_rows, p_rows, self._p_fields, _P_SPEC, P, "p"
        )
        dirty_t = _narrow(
            task_rows, r_rows, self._r_fields, _R_SPEC, T, "r"
        )
        if bump_rows is not None and (dirty_p.size or dirty_t.size):
            self._price[bump_rows] += bump_vals
        n_dp, n_dt = int(dirty_p.size), int(dirty_t.size)
        if n_dp == 0 and n_dt == 0:
            self.last_repair_mask = None
            self.last_stats = {
                "native_isa": native.current_isa(),
                "cold": False, "event": True, "rows": T,
                "cand_cold_passes": 0, "dirty_providers": 0,
                "dirty_tasks": 0, "changed_rows": 0,
                "assigned": int((self._p4t >= 0).sum()),
            }
            return self._p4t.copy()

        eng: Optional[dict] = {} if obs.enabled() else None
        repair, changed = native.repair_topk_candidates(
            _as_ns(self._p_fields, _P_SPEC),
            _as_ns(self._r_fields, _R_SPEC), weights,
            self._cand_p, self._cand_c, self._rev,
            dirty_p, dirty_t,
            k=self._cand_p.shape[1] - self.extra,
            reverse_r=self.reverse_r, extra=self.extra,
            threads=self.threads, coverage_frac=self.coverage_frac,
            slack=(
                (self._slack_p, self._slack_c)
                if self._slack_p is not None else None
            ),
            stats=eng,
        )
        if n_dt:
            # same contract as the batch warm path: a dirty task's seat
            # predates its new requirement — re-seat from scratch
            self._p4t[dirty_t] = -1
        seat_check = np.flatnonzero(repair & (self._p4t >= 0))
        if seat_check.size:
            in_list = (
                self._cand_p[seat_check] == self._p4t[seat_check, None]
            ).any(axis=1)
            lost = seat_check[~in_list]
            if lost.size:
                self._p4t[lost] = -1
                changed[lost] = True
        t_gen = time.perf_counter()

        eps0 = (
            max(float(event_eps_start), self.eps_end)
            if event_eps_start is not None else self.eps_end
        )
        if self.engine == "sinkhorn":
            p4t, price, retired = self._sinkhorn_round(
                P, warm=True,
                retired=self._retired & ~changed,
                seed=self._p4t,
                max_release=self.max_release,
                eng=eng,
            )
        else:
            p4t, price, retired = native.auction_sparse_mt(
                self._cand_p, self._cand_c, num_providers=P,
                eps_start=eps0, eps_end=self.eps_end,
                threads=self.threads,
                price=self._price,
                retired=self._retired & ~changed,
                seed_provider_for_task=self._p4t,
                max_release=self.max_release,
                repair_mask=repair,
                max_events=(
                    self.event_max_bids or 50_000_000
                ),
                stats=eng,
            )
        t_solve = time.perf_counter()
        self._price, self._retired, self._p4t = price, retired, p4t
        # the stream engine's gap tracker needs the touched-row mask
        # (rows whose candidate content moved this event) — exposed as
        # an attribute, never through last_stats (stats flow into JSON
        # trace metrics; arrays do not)
        self.last_repair_mask = repair
        self.last_stats = {
            "native_isa": native.current_isa(),
            "cold": False,
            "event": True,
            "engine": self.engine,
            "rows": T,
            "cand_cold_passes": 0,
            "dirty_providers": n_dp,
            "dirty_tasks": n_dt,
            "changed_rows": int(changed.sum()),
            "repair_rows": int(repair.sum()),
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t_start) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **(self._sink_stats if self.engine == "sinkhorn" else {}),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    def reconcile(self) -> np.ndarray:
        """Full batch re-solve over the CURRENT candidate structure from
        scratch duals — the stream engine's periodic reconciliation.

        Bit-identical to a cold ``solve`` on the current columns WITHOUT
        re-paying the full-matrix candidate pass: the repair exactness
        contract keeps the persistent structure equal to a from-scratch
        rebuild at every event, so "rebuild + cold engine" and "repaired
        structure + cold engine" are the same computation. Re-grounds
        the duals (the per-event warm chain's monotone price ratchet
        resets here, exactly like ``cold_every`` does for batch chains)
        and restarts the starvation clock, mirroring ``_cold``."""
        if self._cand_p is None:
            raise RuntimeError(
                "arena not primed for reconcile: run solve() first"
            )
        t0 = time.perf_counter()
        P = self._p_fields["gpu_count"].shape[0]
        T = self._r_fields["cpu_cores"].shape[0]
        eng: Optional[dict] = {} if obs.enabled() else None
        outs: Optional[dict] = {} if obs.enabled() else None
        prev_p4t = self._p4t.copy() if obs.enabled() else None
        with _tracer.span("arena.engine", engine=self.engine,
                          reconcile=True):
            if self.engine == "sinkhorn":
                self._f = self._g = None
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=False, eng=eng, outs=outs
                )
            else:
                p4t, price, retired = native.auction_sparse_mt(
                    self._cand_p, self._cand_c, num_providers=P,
                    eps_start=self.eps_start, eps_end=self.eps_end,
                    threads=self.threads, stats=eng, outcomes=outs,
                )
        t_solve = time.perf_counter()
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves = 0
        self._dual_age = 0
        self._starve_age = None
        qual = (
            self._quality_pass(
                self._r_fields, p4t, price, prev_p4t, outs, eng
            )
            if obs.enabled() else {}
        )
        self.last_stats = {
            "native_isa": native.current_isa(),
            **qual,
            "cold": False,
            "reconcile": True,
            "engine": self.engine,
            "rows": T,
            "cand_cold_passes": 0,
            "dirty_providers": 0,
            "dirty_tasks": 0,
            "changed_rows": 0,
            "assigned": int((p4t >= 0).sum()),
            "solve_ms": round((t_solve - t0) * 1e3, 3),
            **(self._sink_stats if self.engine == "sinkhorn" else {}),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    # ---------------- the solve ----------------

    def solve(self, ep, er, weights) -> np.ndarray:
        """One marketplace solve. ``ep``/``er`` are EncodedProviders /
        EncodedRequirements (numpy- or jax-backed); returns
        provider_for_task [T] i32. ``last_stats`` reports what was
        recomputed (plus, with the obs plane on, ``gen_ms``/``solve_ms``
        stage walls and flattened ``eng_*`` native engine phase stats —
        bidding rounds, eviction counts, per-phase ns — which ride
        OUTCOME frames and the obs report).

        Dirty detection compares against the arrays of the PREVIOUS call,
        which the arena holds by reference (copying every feature column
        per solve would cost ~150 MB/solve at 1M rows): callers must pass
        freshly-built or copied arrays rather than mutating the previous
        call's buffers in place (the matcher re-encodes per solve, and
        jax-backed arrays are immutable, so both production paths are
        safe by construction)."""
        with _tracer.span("arena.solve", engine=self.engine):
            return self._solve_impl(ep, er, weights)

    def _solve_impl(self, ep, er, weights) -> np.ndarray:
        pf = _canon(ep, _P_SPEC)
        rf = _canon(er, _R_SPEC)
        P = pf["gpu_count"].shape[0]
        T = rf["cpu_cores"].shape[0]
        if P == 0 or T == 0:
            self.last_stats = {
                "native_isa": native.current_isa(),
                "cold": True, "assigned": 0,
            }
            return np.full(T, -1, np.int32)

        if (
            not self._shapes_compatible(pf, rf)
            # every carried cost and selection was computed under the old
            # weights: a weight change invalidates the whole structure
            or self._weights_key != self._wkey(weights)
            or self._warm_solves >= self.cold_every
        ):
            return self._cold(ep, er, weights, pf, rf, P, T)

        dirty_p = _dirty_rows(pf, self._p_fields, _P_SPEC)
        dirty_t = _dirty_rows(rf, self._r_fields, _R_SPEC)
        # struct/base split is OBSERVABILITY only now: the repair kernel
        # treats price/load drift as churn like any other (its exactness
        # contract re-scores the drifted columns — the historical
        # in-place cost shift kept candidate membership stale between
        # cold re-grounds, which the persistent structure no longer
        # tolerates). The cost: a fleet-wide reprice is a full dirty set
        # and honestly falls back to one cold-equivalent rebuild via
        # max_dirty_frac instead of pretending to stay warm on stale
        # selections.
        struct_dirty_p = _dirty_rows(
            pf, self._p_fields,
            [s for s in _P_SPEC if s[0] not in ("price", "load")],
        )
        base_only = dirty_p & ~struct_dirty_p
        n_dp_all, n_dt = int(dirty_p.sum()), int(dirty_t.sum())
        n_dp = int(struct_dirty_p.sum())
        n_base = int(base_only.sum())
        if (n_dp_all + n_dt) / (P + T) > self.max_dirty_frac or (
            # the incremental repair needs the bidirectional structure
            # (reverse keys) to exist; without it every churn re-grounds
            (n_dp_all or n_dt) and self._rev is None
        ):
            return self._cold(ep, er, weights, pf, rf, P, T)
        if n_dp_all == 0 and n_dt == 0:
            # byte-identical marketplace: the carried matching IS the
            # solve (prices/retirement already consistent with it)
            self._warm_solves += 1
            qual: dict = {}
            if obs.enabled():
                # nothing changed, so the carried gap/outcome
                # certificate is still exact — reuse it instead of
                # re-scanning [T x K]; only the tick-indexed signals
                # (starvation ages, zero churn) advance
                t_q = time.perf_counter()
                self._starve_age = _quality.starvation_update(
                    self._starve_age, self._p4t,
                    rf["valid"].astype(bool),
                )
                qual = dict(self._last_quality)
                qual["churn_rows"] = 0
                qual["churn_ratio"] = 0.0
                qual["starve_max"] = (
                    int(self._starve_age.max())
                    if self._starve_age.size else 0
                )
                qual["starving"] = int((self._starve_age > 0).sum())
                qual["starve_hist"] = _quality.starvation_hist(
                    self._starve_age
                )
                qual["quality_ms"] = round(
                    (time.perf_counter() - t_q) * 1e3, 3
                )
                self._last_quality = qual
            self.last_stats = {
                "native_isa": native.current_isa(),
                **qual,
                "cold": False,
                "rows": T,
                "cand_cold_passes": 0,
                "dirty_providers": 0,
                "dirty_tasks": 0,
                "changed_rows": 0,
                "warm_solves_since_cold": self._warm_solves,
                "assigned": int((self._p4t >= 0).sum()),
            }
            return self._p4t.copy()

        eng: Optional[dict] = {} if obs.enabled() else None
        outs: Optional[dict] = {} if obs.enabled() else None
        # the previous tick's plan, captured BEFORE the dirty-task
        # re-seat below mutates it in place — the churn ratio compares
        # plan-to-plan, not plan-to-scratchpad
        prev_p4t = self._p4t.copy() if obs.enabled() else None
        t_start = time.perf_counter()
        self._p_fields, self._r_fields = pf, rf
        self._owned_cols = set()

        # ---- incremental repair: one native pass rewrites the persistent
        # structure (forward lists + reverse keys + extras) in place,
        # bit-identical to a from-scratch rebuild on the current columns,
        # touching only what the dirty sets reach. ``repair`` (touched
        # rows — costs moved in either direction) is the only set whose
        # eps-CS happiness can degrade (prices are monotone), so it is
        # the only set the warm auction re-scans; ``changed`` is the
        # retirement-clearing set (membership moved or materially
        # cheaper — pure cost increases cannot un-retire).
        repair, changed = native.repair_topk_candidates(
            _as_ns(pf, _P_SPEC), _as_ns(rf, _R_SPEC), weights,
            self._cand_p, self._cand_c, self._rev,
            np.flatnonzero(dirty_p).astype(np.int32),
            np.flatnonzero(dirty_t).astype(np.int32),
            k=self._cand_p.shape[1] - self.extra,
            reverse_r=self.reverse_r, extra=self.extra,
            threads=self.threads, coverage_frac=self.coverage_frac,
            slack=(
                (self._slack_p, self._slack_c)
                if self._slack_p is not None else None
            ),
            stats=eng,
        )
        if n_dt:
            # a dirty task's seat predates its new requirement: re-seat
            # from scratch (the warm repair would keep a stale-but-eps-OK
            # seat on candidates the task no longer declares)
            self._p4t[np.flatnonzero(dirty_t)] = -1

        # ---- feasibility guard: a seat whose provider left the row's
        # candidate list (churn dropped it, or an entering cheaper
        # provider displaced it in the repair) must be unseated HERE, not
        # left to the auction's eps-CS repair — with max_release capping
        # the repair, an over-cap infeasible seat would persist and then
        # be skipped by later repair masks (its row no longer churns).
        # Only rows whose lists moved this solve (repair mask) can have
        # lost their seat.
        seat_check = np.flatnonzero(repair & (self._p4t >= 0))
        if seat_check.size:
            in_list = (
                self._cand_p[seat_check]
                == self._p4t[seat_check, None]
            ).any(axis=1)
            lost = seat_check[~in_list]
            if lost.size:
                self._p4t[lost] = -1
                changed[lost] = True  # unseated: must be free to re-bid

        t_gen = time.perf_counter()
        _tracer.record_span(
            "arena.candidates", int(t_start * 1e9),
            int((t_gen - t_start) * 1e9), cold=False,
            dirty_providers=n_dp, dirty_tasks=n_dt, base_only=n_base,
        )
        # ---- solve over the (updated) cached candidate structure:
        # warm dual carry on most ticks, a full dual refresh on schedule
        dual_refresh = (
            self.dual_refresh_every > 0
            and self._dual_age >= self.dual_refresh_every
        )
        if self.engine == "sinkhorn":
            # entropic potentials re-converge from the carried (f, g) —
            # the dual refresh re-grounds only the REFEREE's retirement/
            # seeding (the cardinality-bleed half), never the potentials:
            # sinkhorn duals are a fixed point recomputed in full every
            # solve, so they cannot ratchet the way auction prices do
            if dual_refresh:
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=True, eng=eng, outs=outs
                )
                self._dual_age = 0
            else:
                p4t, price, retired = self._sinkhorn_round(
                    P, warm=True,
                    retired=self._retired & ~changed,
                    seed=self._p4t,
                    max_release=self.max_release,
                    eng=eng, outs=outs,
                )
                self._dual_age += 1
        elif dual_refresh:
            p4t, price, retired = native.auction_sparse_mt(
                self._cand_p, self._cand_c, num_providers=P,
                eps_start=self.eps_start, eps_end=self.eps_end,
                threads=self.threads, stats=eng, outcomes=outs,
            )
            self._dual_age = 0
        else:
            retired = self._retired & ~changed
            p4t, price, retired = native.auction_sparse_mt(
                self._cand_p, self._cand_c, num_providers=P,
                eps_start=max(self.warm_eps_start, self.eps_end),
                eps_end=self.eps_end,
                threads=self.threads,
                price=self._price, retired=retired,
                seed_provider_for_task=self._p4t,
                max_release=self.max_release,
                repair_mask=repair,
                stats=eng, outcomes=outs,
            )
            self._dual_age += 1
        t_solve = time.perf_counter()
        _tracer.record_span(
            "arena.engine", int(t_gen * 1e9),
            int((t_solve - t_gen) * 1e9), engine=self.engine, cold=False,
        )
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves += 1
        qual = (
            self._quality_pass(rf, p4t, price, prev_p4t, outs, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            "native_isa": native.current_isa(),
            **qual,
            "cold": False,
            "engine": self.engine,
            "rows": T,
            "cand_cold_passes": 0,
            "dual_refresh": dual_refresh,
            "dirty_providers": n_dp,
            "base_only_providers": n_base,
            "dirty_tasks": n_dt,
            "changed_rows": int(changed.sum()),
            "warm_solves_since_cold": self._warm_solves,
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t_start) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **(self._sink_stats if self.engine == "sinkhorn" else {}),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t
