"""Persistent warm-solve arena for the native CPU engine (engine=native-mt).

The degraded-mode twin of the CandidateCache + warm-kernel pipeline
(sched/cand_cache.py + ops/sparse.assign_auction_sparse_warm): repeated
solves against an incrementally-churned marketplace reuse everything that
survives between ticks instead of rebuilding it —

  - **Candidate structure.** The fused cost+top-k pass is the dominant
    stage (~90% of a cold native solve). The arena keeps the assembled
    [T, k+extra] bidirectional candidate lists and, on churn, recomputes
    only the rows that can have changed: dirty TASKS get a fresh fused
    pass against the full fleet; dirty PROVIDERS are dropped from every
    cached list and re-merged from one [dirty-P x T] delta pass (their
    forward candidates AND their reverse edges) — never the full pass.
  - **Auction dual state.** Prices per provider, the retirement mask per
    task, and the previous matching are carried into a single-phase warm
    auction (native.auction_sparse_mt), whose eps-CS repair evicts stale
    seeds. Retirement flags are cleared for exactly the rows whose
    candidates changed — the same caller contract the JAX warm kernel
    documents ("rows whose costs or candidates changed must be cleared").

Dirty detection is value-based: each provider/requirement feature column
is compared row-wise against the previous solve's columns, so any change
that can affect feasibility or cost (specs, price, load, validity, the
requirement DSL fields) marks its row dirty and ONLY that row is
recomputed. Two staleness backstops mirror the TPU path: a dirty fraction
above ``max_dirty_frac`` triggers a full rebuild (the delta pass would
cost more than it saves), and ``cold_every`` bounds tie-jitter drift from
delta passes (delta candidates are jittered by their local indices, like
the CandidateCache's merge batches) plus the warm chain's monotone price
ratchet.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from protocol_tpu import native

# canonical dtypes per encoded field (mirrors native.fused_topk_candidates'
# coercions so comparing cached vs incoming columns is exact)
_P_SPEC = (
    ("gpu_count", np.int32), ("gpu_mem_mb", np.int32),
    ("gpu_model_id", np.int32), ("has_gpu", np.uint8),
    ("has_cpu", np.uint8), ("cpu_cores", np.int32), ("ram_mb", np.int32),
    ("storage_gb", np.int32), ("lat", np.float32), ("lon", np.float32),
    ("has_location", np.uint8), ("price", np.float32), ("load", np.float32),
    ("valid", np.uint8),
)
_R_SPEC = (
    ("cpu_required", np.uint8), ("cpu_cores", np.int32), ("ram_mb", np.int32),
    ("storage_gb", np.int32), ("gpu_opt_valid", np.uint8),
    ("gpu_count", np.int32), ("gpu_mem_min", np.int32),
    ("gpu_mem_max", np.int32), ("gpu_total_mem_min", np.int32),
    ("gpu_total_mem_max", np.int32), ("gpu_model_mask", np.uint32),
    ("gpu_model_constrained", np.uint8), ("lat", np.float32),
    ("lon", np.float32), ("has_location", np.uint8),
    ("priority", np.float32), ("valid", np.uint8),
)


def _canon(enc, spec) -> dict[str, np.ndarray]:
    return {
        name: np.ascontiguousarray(np.asarray(getattr(enc, name)), dtype)
        for name, dtype in spec
    }


def _dirty_rows(new: dict, old: dict, spec) -> np.ndarray:
    """Row-wise OR of per-field inequality (trailing axes collapsed)."""
    n = new[spec[0][0]].shape[0]
    dirty = np.zeros(n, bool)
    for name, _ in spec:
        diff = new[name] != old[name]
        dirty |= diff.reshape(n, -1).any(axis=1)
    return dirty


def _subset(fields: dict, idx: np.ndarray, spec) -> object:
    """A namespace with the gathered rows of each field (duck-types the
    Encoded* dataclasses for native.fused_topk_candidates)."""
    ns = type("_Sub", (), {})()
    for name, _ in spec:
        setattr(ns, name, fields[name][idx])
    return ns


def _as_ns(fields: dict, spec) -> object:
    ns = type("_Full", (), {})()
    for name, _ in spec:
        setattr(ns, name, fields[name])
    return ns


class NativeSolveArena:
    def __init__(
        self,
        k: int = 64,
        reverse_r: int = 8,
        extra: int = 16,
        threads: int = 0,
        cold_every: int = 256,
        max_dirty_frac: float = 0.25,
        eps_start: float = 4.0,
        eps_end: float = 0.02,
    ):
        self.k = k
        self.reverse_r = reverse_r
        self.extra = extra
        self.threads = threads
        self.cold_every = cold_every
        self.max_dirty_frac = max_dirty_frac
        self.eps_start = eps_start
        self.eps_end = eps_end
        self.last_stats: dict = {}
        self.invalidate()

    @property
    def price(self) -> Optional[np.ndarray]:
        """Carried auction prices [P] after the last solve (dual state)."""
        return self._price

    @property
    def retired(self) -> Optional[np.ndarray]:
        """Carried retirement mask [T] after the last solve."""
        return self._retired

    def invalidate(self) -> None:
        """Drop all carried state: the next solve is cold."""
        self._p_fields: Optional[dict] = None
        self._r_fields: Optional[dict] = None
        self._weights_key: Optional[tuple] = None
        self._cand_p: Optional[np.ndarray] = None
        self._cand_c: Optional[np.ndarray] = None
        self._price: Optional[np.ndarray] = None
        self._retired: Optional[np.ndarray] = None
        self._p4t: Optional[np.ndarray] = None
        self._warm_solves = 0

    # ---------------- internals ----------------

    @staticmethod
    def _wkey(weights) -> tuple:
        return (
            float(weights.price), float(weights.load),
            float(weights.proximity), float(weights.priority),
        )

    def _shapes_compatible(self, pf: dict, rf: dict) -> bool:
        old_p, old_r = self._p_fields, self._r_fields
        if old_p is None or old_r is None:
            return False
        return all(
            pf[n].shape == old_p[n].shape for n, _ in _P_SPEC
        ) and all(rf[n].shape == old_r[n].shape for n, _ in _R_SPEC)

    def _cold(self, ep, er, weights, pf, rf, P, T) -> np.ndarray:
        cand_p, cand_c = native.fused_topk_candidates(
            ep, er, weights, k=self.k, reverse_r=self.reverse_r,
            extra=self.extra, threads=self.threads,
        )
        p4t, price, retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P,
            eps_start=self.eps_start, eps_end=self.eps_end,
            threads=self.threads,
        )
        self._p_fields, self._r_fields = pf, rf
        self._weights_key = self._wkey(weights)
        self._cand_p, self._cand_c = cand_p, cand_c
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves = 0
        self.last_stats = {
            "cold": True,
            "dirty_providers": P,
            "dirty_tasks": T,
            "changed_rows": T,
            "warm_solves_since_cold": 0,
            "assigned": int((p4t >= 0).sum()),
        }
        return p4t

    def _merge_delta(
        self,
        rows: np.ndarray,
        dirty_p_idx: np.ndarray,
        delta_p: np.ndarray,
        delta_c: np.ndarray,
    ) -> np.ndarray:
        """For the task rows in ``rows``: drop dirty providers from the
        cached row, fold the delta pass's candidates (forward + reverse,
        global ids) back in by current cost, and return the changed mask
        (aligned with ``rows``). Rows recomputed this solve are excluded
        by the caller — re-merging them would duplicate dirty providers
        inside one candidate list (a dup makes v1 == v2 in the bid math)."""
        cand_p = self._cand_p[rows]
        cand_c = self._cand_c[rows]
        in_dirty = np.zeros(self._price.shape[0], bool)
        in_dirty[dirty_p_idx] = True
        stale = (cand_p >= 0) & in_dirty[np.maximum(cand_p, 0)]
        masked_p = np.where(stale, -1, cand_p)

        allp = np.concatenate([masked_p, delta_p[rows]], axis=1)
        allc = np.concatenate([cand_c, delta_c[rows]], axis=1)
        key = np.where(allp >= 0, allc, np.inf)
        k_eff = cand_p.shape[1]
        idx = np.argsort(key, axis=1, kind="stable")[:, :k_eff]
        new_p = np.take_along_axis(allp, idx, axis=1).astype(np.int32)
        new_c = np.take_along_axis(allc, idx, axis=1).astype(np.float32)
        new_c[new_p < 0] = 0.0
        # changed = provider set/order moved OR a kept candidate got
        # materially CHEAPER (same row, lower cost — e.g. a price drop
        # that doesn't re-rank): both can make a retired task viable
        # again, so both must clear its carried flag. Increases cannot
        # un-retire; the 0.05 floor matches the CandidateCache's
        # stale_abs_tol ("drift big enough to matter").
        changed = (new_p != cand_p).any(axis=1) | (
            (cand_c - new_c) > 0.05
        ).any(axis=1)
        self._cand_p[rows] = new_p
        self._cand_c[rows] = new_c
        return changed

    # ---------------- the solve ----------------

    def solve(self, ep, er, weights) -> np.ndarray:
        """One marketplace solve. ``ep``/``er`` are EncodedProviders /
        EncodedRequirements (numpy- or jax-backed); returns
        provider_for_task [T] i32. ``last_stats`` reports what was
        recomputed.

        Dirty detection compares against the arrays of the PREVIOUS call,
        which the arena holds by reference (copying every feature column
        per solve would cost ~150 MB/solve at 1M rows): callers must pass
        freshly-built or copied arrays rather than mutating the previous
        call's buffers in place (the matcher re-encodes per solve, and
        jax-backed arrays are immutable, so both production paths are
        safe by construction)."""
        pf = _canon(ep, _P_SPEC)
        rf = _canon(er, _R_SPEC)
        P = pf["gpu_count"].shape[0]
        T = rf["cpu_cores"].shape[0]
        if P == 0 or T == 0:
            self.last_stats = {"cold": True, "assigned": 0}
            return np.full(T, -1, np.int32)

        if (
            not self._shapes_compatible(pf, rf)
            # every carried cost and selection was computed under the old
            # weights: a weight change invalidates the whole structure
            or self._weights_key != self._wkey(weights)
            or self._warm_solves >= self.cold_every
        ):
            return self._cold(ep, er, weights, pf, rf, P, T)

        dirty_p = _dirty_rows(pf, self._p_fields, _P_SPEC)
        dirty_t = _dirty_rows(rf, self._r_fields, _R_SPEC)
        n_dp, n_dt = int(dirty_p.sum()), int(dirty_t.sum())
        if (n_dp + n_dt) / (P + T) > self.max_dirty_frac:
            return self._cold(ep, er, weights, pf, rf, P, T)
        if n_dp == 0 and n_dt == 0:
            # byte-identical marketplace: the carried matching IS the
            # solve (prices/retirement already consistent with it)
            self._warm_solves += 1
            self.last_stats = {
                "cold": False,
                "dirty_providers": 0,
                "dirty_tasks": 0,
                "changed_rows": 0,
                "warm_solves_since_cold": self._warm_solves,
                "assigned": int((self._p4t >= 0).sum()),
            }
            return self._p4t.copy()

        self._p_fields, self._r_fields = pf, rf
        changed = dirty_t.copy()

        # ---- dirty tasks: fresh fused pass against the full fleet
        if n_dt:
            t_idx = np.flatnonzero(dirty_t)
            sub_er = _subset(rf, t_idx, _R_SPEC)
            tp, tc = native.fused_topk_candidates(
                _as_ns(pf, _P_SPEC), sub_er, weights, k=self.k,
                reverse_r=self.reverse_r, extra=self.extra,
                threads=self.threads,
            )
            self._cand_p[t_idx] = tp
            self._cand_c[t_idx] = tc
            # a dirty task's seat predates its new requirement: re-seat
            # from scratch (the warm repair would keep a stale-but-eps-OK
            # seat on candidates the task no longer declares)
            self._p4t[t_idx] = -1

        # ---- dirty providers: one [dirty-P x T] delta pass, merged into
        # every row NOT already recomputed above
        if n_dp:
            p_idx = np.flatnonzero(dirty_p)
            sub_ep = _subset(pf, p_idx, _P_SPEC)
            kd = min(self.k, n_dp)
            dp_local, dc = native.fused_topk_candidates(
                sub_ep, _as_ns(rf, _R_SPEC), weights, k=kd,
                reverse_r=self.reverse_r, extra=self.extra,
                threads=self.threads,
            )
            # local -> global provider ids
            dp = np.where(
                dp_local >= 0, p_idx[np.maximum(dp_local, 0)], -1
            ).astype(np.int32)
            keep_rows = np.flatnonzero(~dirty_t)
            if keep_rows.size:
                changed[keep_rows] |= self._merge_delta(
                    keep_rows, p_idx, dp, dc
                )

        # ---- warm auction over the carried dual state
        retired = self._retired & ~changed
        p4t, price, retired = native.auction_sparse_mt(
            self._cand_p, self._cand_c, num_providers=P,
            eps_start=self.eps_end, eps_end=self.eps_end,
            threads=self.threads,
            price=self._price, retired=retired,
            seed_provider_for_task=self._p4t,
        )
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves += 1
        self.last_stats = {
            "cold": False,
            "dirty_providers": n_dp,
            "dirty_tasks": n_dt,
            "changed_rows": int(changed.sum()),
            "warm_solves_since_cold": self._warm_solves,
            "assigned": int((p4t >= 0).sum()),
        }
        return p4t
