"""Wallet identity, request signing, and signature-validation middleware.

The reference authenticates every HTTP request between untrusted parties
with an Ethereum wallet signature over ``endpoint + sorted-JSON body`` plus
a nonce (crates/shared/src/security/). This package keeps that protocol
shape — ``x-address`` / ``x-signature`` headers, nonce replay cache, rate
limiting, body caps — over Ed25519 (cryptography package) instead of
secp256k1: Ed25519 has no public-key recovery, so the signature value
carries the public key and the verifier checks it hashes to the claimed
address.
"""

from protocol_tpu.security.wallet import Wallet, verify_signature
from protocol_tpu.security.signer import sign_request, verify_request

__all__ = ["Wallet", "sign_request", "verify_request", "verify_signature"]
