"""Wallet identity, request signing, and signature-validation middleware.

The reference authenticates every HTTP request between untrusted parties
with an Ethereum wallet signature over ``endpoint + sorted-JSON body`` plus
a nonce (crates/shared/src/security/). This package keeps that protocol
shape — ``x-address`` / ``x-signature`` headers, nonce replay cache, rate
limiting, body caps — over three interchangeable schemes behind one
verifier: Ed25519 (:class:`Wallet`, the default), secp256k1/keccak with
an embedded pubkey (:class:`EvmWallet` — real Ethereum addresses, cheap
verification), and the reference's literal recovery wire
(:class:`EvmRecoveryWallet` — 0x + r||s||v over the EIP-191 digest,
verified by pure-Python public-key recovery, so signatures from alloy/
MetaMask-style clients authenticate verbatim).
"""

from protocol_tpu.security.wallet import (
    EvmRecoveryWallet,
    EvmWallet,
    Wallet,
    verify_signature,
)
from protocol_tpu.security.signer import sign_request, verify_request

__all__ = [
    "EvmRecoveryWallet",
    "EvmWallet",
    "Wallet",
    "sign_request",
    "verify_request",
    "verify_signature",
]
