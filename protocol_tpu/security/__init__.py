"""Wallet identity, request signing, and signature-validation middleware.

The reference authenticates every HTTP request between untrusted parties
with an Ethereum wallet signature over ``endpoint + sorted-JSON body`` plus
a nonce (crates/shared/src/security/). This package keeps that protocol
shape — ``x-address`` / ``x-signature`` headers, nonce replay cache, rate
limiting, body caps — over two interchangeable schemes behind one verifier:
Ed25519 (:class:`Wallet`, the default) and secp256k1/keccak
(:class:`EvmWallet`, the reference's exact scheme with real Ethereum
addresses). Neither uses public-key recovery on the wire: the signature
value carries the public key and the verifier checks it hashes to the
claimed address.
"""

from protocol_tpu.security.wallet import EvmWallet, Wallet, verify_signature
from protocol_tpu.security.signer import sign_request, verify_request

__all__ = [
    "EvmWallet",
    "Wallet",
    "sign_request",
    "verify_request",
    "verify_signature",
]
