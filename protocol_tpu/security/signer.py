"""Request signing: nonce injection + sorted-key JSON + endpoint binding.

Reference: crates/shared/src/security/request_signer.rs:22-68 —
``sign_request_with_nonce`` inserts a uuid nonce into the JSON body, sorts
object keys recursively, and signs ``endpoint + json``. Same scheme here;
the verifier recomputes the canonical JSON from the received body.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Optional

from protocol_tpu.security.wallet import Wallet, verify_signature


def canonical_json(body: Any) -> str:
    """Deterministic JSON: recursively sorted keys, compact separators."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


def sign_request(
    endpoint: str,
    wallet: Wallet,
    body: Optional[dict] = None,
) -> tuple[dict[str, str], Optional[dict]]:
    """Returns (headers, body-with-nonce).

    Signed message = endpoint + x-timestamp (+ canonical body JSON). The
    timestamp is signed so bodyless (GET-style) requests are replayable only
    within the middleware's freshness window — the body-nonce cache does not
    cover them.
    """
    import time

    timestamp = f"{time.time():.6f}"
    signed_body = None
    message = endpoint + timestamp
    if body is not None:
        signed_body = dict(body)
        signed_body["nonce"] = uuid.uuid4().hex  # 32 alnum chars
        message += canonical_json(signed_body)
    signature = wallet.sign_message(message)
    return {
        "x-address": wallet.address,
        "x-signature": signature,
        "x-timestamp": timestamp,
    }, signed_body


def verify_request(
    endpoint: str,
    headers: dict[str, str],
    body: Optional[dict] = None,
) -> Optional[str]:
    """Validates headers against the endpoint+timestamp+body; returns the
    authenticated address, or None. Freshness of x-timestamp is enforced by
    the middleware, not here."""
    address = headers.get("x-address")
    signature = headers.get("x-signature")
    timestamp = headers.get("x-timestamp")
    if not address or not signature or timestamp is None:
        return None
    message = endpoint + timestamp
    if body is not None:
        message += canonical_json(body)
    if verify_signature(message, signature, address):
        return address.lower()
    return None
