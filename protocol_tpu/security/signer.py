"""Request signing: nonce injection + sorted-key JSON + endpoint binding.

Reference: crates/shared/src/security/request_signer.rs:22-68 —
``sign_request_with_nonce`` inserts a uuid nonce into the JSON body, sorts
object keys recursively, and signs ``endpoint + json``. Same scheme here;
the verifier recomputes the canonical JSON from the received body.

Oversized bodies sign a DIGEST instead of the raw JSON: the EVM wallet
schemes keccak the signed message in pure Python and therefore cap it at
EVM_MAX_MESSAGE_BYTES (64 KB) — which a hardware-challenge payload
(~254 KB of matrices at the default challenge_size=64) blows through,
aborting the whole validation tick under PROTOCOL_TPU_WALLET_SCHEME=evm.
Above ``BODY_DIGEST_THRESHOLD`` the signed message carries
``sha256:<hexdigest of the canonical JSON>`` in the body's place and the
``x-body-digest: sha256`` header tells the verifier to hash the received
body the same way. Binding is unchanged (the digest commits to every
body byte); the prefix cannot collide with a literal canonical JSON
(which always starts with a JSON token, never ``s``); and stripping or
adding the header just changes which message the verifier reconstructs,
so a tampered request still fails signature verification.
"""

from __future__ import annotations

import hashlib
import json
import uuid
from typing import Any, Optional

from protocol_tpu.security.wallet import Wallet, verify_signature

# Stay comfortably under EVM_MAX_MESSAGE_BYTES (64 KB): the endpoint,
# timestamp, and digest prefix ride in the same signed message.
BODY_DIGEST_THRESHOLD = 48 * 1024
BODY_DIGEST_HEADER = "x-body-digest"


def canonical_json(body: Any) -> str:
    """Deterministic JSON: recursively sorted keys, compact separators."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


def _body_digest(payload: str) -> str:
    return "sha256:" + hashlib.sha256(payload.encode()).hexdigest()


def sign_request(
    endpoint: str,
    wallet: Wallet,
    body: Optional[dict] = None,
) -> tuple[dict[str, str], Optional[dict]]:
    """Returns (headers, body-with-nonce).

    Signed message = endpoint + x-timestamp (+ canonical body JSON). The
    timestamp is signed so bodyless (GET-style) requests are replayable only
    within the middleware's freshness window — the body-nonce cache does not
    cover them.
    """
    import time

    timestamp = f"{time.time():.6f}"
    signed_body = None
    message = endpoint + timestamp
    headers = {"x-address": wallet.address, "x-timestamp": timestamp}
    if body is not None:
        signed_body = dict(body)
        signed_body["nonce"] = uuid.uuid4().hex  # 32 alnum chars
        payload = canonical_json(signed_body)
        if len(payload) > BODY_DIGEST_THRESHOLD:
            # digest mode: keeps large payloads (challenge matrices) off
            # the keccak-capped signing plane for every wallet scheme
            message += _body_digest(payload)
            headers[BODY_DIGEST_HEADER] = "sha256"
        else:
            message += payload
    headers["x-signature"] = wallet.sign_message(message)
    return headers, signed_body


def verify_request(
    endpoint: str,
    headers: dict[str, str],
    body: Optional[dict] = None,
) -> Optional[str]:
    """Validates headers against the endpoint+timestamp+body; returns the
    authenticated address, or None. Freshness of x-timestamp is enforced by
    the middleware, not here."""
    address = headers.get("x-address")
    signature = headers.get("x-signature")
    timestamp = headers.get("x-timestamp")
    if not address or not signature or timestamp is None:
        return None
    message = endpoint + timestamp
    if body is not None:
        payload = canonical_json(body)
        if headers.get(BODY_DIGEST_HEADER) == "sha256":
            # digest-signed body (see module docstring): hash the received
            # bytes the same way the signer did — a header added, removed,
            # or altered in transit reconstructs a different message and
            # the signature fails, so there is no downgrade path
            message += _body_digest(payload)
        else:
            message += payload
    if verify_signature(message, signature, address):
        return address.lower()
    return None
