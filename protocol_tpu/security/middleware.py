"""aiohttp middlewares: signature validation and admin API key.

Reference: crates/shared/src/security/auth_signature_middleware.rs —
actix ``ValidateSignature`` transform with nonce format check (16-64
alphanumeric, :135-140), Redis nonce replay cache with 60 s TTL (:159-180),
in-memory rate limit 100 req/min/address (:142-157), 10 MB body cap
(:27-35), plus optional per-service validators (e.g. "node exists and is
not ejected", orchestrator/src/api/server.rs:170-185) — and
api_key_middleware.rs (``Authorization: Bearer <admin key>``).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Awaitable, Callable, Iterable, Optional

from aiohttp import web

from protocol_tpu.security.signer import verify_request
from protocol_tpu.store.kv import KVStore

NONCE_TTL_SECONDS = 60.0
RATE_LIMIT_PER_MINUTE = 100
MAX_BODY_BYTES = 10 * 1024 * 1024

AddressValidator = Callable[[str], Awaitable[bool]]


def _nonce_valid(nonce: str) -> bool:
    return 16 <= len(nonce) <= 64 and nonce.isalnum()


class RateLimiter:
    """Fixed-window per-address counter (middleware.rs:142-157)."""

    def __init__(self, limit: int = RATE_LIMIT_PER_MINUTE, window: float = 60.0):
        self.limit = limit
        self.window = window
        self._counts: dict[str, tuple[int, float]] = {}

    def allow(self, address: str, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        count, start = self._counts.get(address, (0, now))
        if now - start >= self.window:
            count, start = 0, now
        if count >= self.limit:
            return False
        self._counts[address] = (count + 1, start)
        return True


def validate_signature_middleware(
    kv: KVStore,
    protected_prefixes: Iterable[str],
    validator: Optional[AddressValidator] = None,
    allowed_addresses: Optional[Iterable[str]] = None,
    rate_limiter: Optional[RateLimiter] = None,
    max_body_bytes: int = MAX_BODY_BYTES,
):
    """Middleware guarding the given path prefixes with wallet signatures.

    On success, the authenticated address is stored as
    ``request["auth_address"]``.
    """
    prefixes = tuple(protected_prefixes)
    limiter = rate_limiter or RateLimiter()
    # None = no address filtering; an EMPTY allowlist fails closed (rejects
    # every address) — callers that want an open surface must pass None
    # explicitly rather than an empty list.
    allow = (
        {a.lower() for a in allowed_addresses}
        if allowed_addresses is not None
        else None
    )

    @web.middleware
    async def middleware(request: web.Request, handler):
        if not any(request.path.startswith(p) for p in prefixes):
            return await handler(request)

        if request.content_length and request.content_length > max_body_bytes:
            return web.json_response(
                {"success": False, "error": "body too large"}, status=413
            )

        body = None
        if request.method in ("POST", "PUT", "PATCH", "DELETE") and request.can_read_body:
            raw = await request.read()
            if len(raw) > max_body_bytes:
                return web.json_response(
                    {"success": False, "error": "body too large"}, status=413
                )
            if raw:
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError:
                    return web.json_response(
                        {"success": False, "error": "invalid json"}, status=400
                    )

        # signed-timestamp freshness: bounds replay of bodyless (GET) requests
        # to the skew window; body requests additionally carry the nonce cache
        try:
            ts = float(request.headers.get("x-timestamp", ""))
        except ValueError:
            return web.json_response(
                {"success": False, "error": "missing timestamp"}, status=401
            )
        if abs(time.time() - ts) > NONCE_TTL_SECONDS:
            return web.json_response(
                {"success": False, "error": "stale timestamp"}, status=401
            )

        # allowlist gate runs BEFORE verification on the CLAIMED address:
        # rejecting a never-allowed address needs no crypto, and the
        # secp/keccak verify path is CPU work an unauthenticated stranger
        # should not get to purchase
        claimed = (request.headers.get("x-address") or "").lower()
        if allow is not None and claimed not in allow:
            return web.json_response(
                {"success": False, "error": "address not allowed"}, status=401
            )

        # pass the CIMultiDict through: its .get is case-insensitive, so
        # clients sending X-Address/X-Signature (standard casing) still
        # authenticate. Verification runs in a thread: Ed25519 is
        # C-speed, but the EvmWallet path keccaks the full message in
        # Python (capped at EVM_MAX_MESSAGE_BYTES) — the event loop must
        # not stall behind it
        address = await asyncio.to_thread(
            verify_request, request.path, request.headers, body
        )
        if address is None:
            return web.json_response(
                {"success": False, "error": "invalid signature"}, status=401
            )

        if body is None:
            # replay-cache the signature itself for the freshness window.
            # kv.set runs in a thread: with a RemoteKVStore (api-mode
            # replicas) it is a blocking HTTP round-trip
            sig = request.headers.get("x-signature", "")
            fresh = await asyncio.to_thread(
                kv.set, f"sig:{sig}", "1", nx=True, ex=NONCE_TTL_SECONDS * 2
            )
            if not fresh:
                return web.json_response(
                    {"success": False, "error": "signature replay"}, status=401
                )

        if not limiter.allow(address):
            return web.json_response(
                {"success": False, "error": "rate limited"}, status=429
            )

        # nonce: required on signed bodies; format-checked and replay-cached
        if body is not None:
            nonce = body.get("nonce")
            if not nonce or not _nonce_valid(str(nonce)):
                return web.json_response(
                    {"success": False, "error": "invalid nonce"}, status=401
                )
            fresh = await asyncio.to_thread(
                kv.set, f"nonce:{nonce}", "1", nx=True, ex=NONCE_TTL_SECONDS
            )
            if not fresh:
                return web.json_response(
                    {"success": False, "error": "nonce replay"}, status=401
                )

        if validator is not None and not await validator(address):
            return web.json_response(
                {"success": False, "error": "address rejected"}, status=401
            )

        request["auth_address"] = address
        request["auth_body"] = body
        return await handler(request)

    return middleware


def api_key_middleware(api_key: str, protected_prefixes: Iterable[str]):
    """``Authorization: Bearer <key>`` guard for admin routes
    (api_key_middleware.rs)."""
    prefixes = tuple(protected_prefixes)

    @web.middleware
    async def middleware(request: web.Request, handler):
        if not any(request.path.startswith(p) for p in prefixes):
            return await handler(request)
        header = request.headers.get("Authorization", "")
        if header != f"Bearer {api_key}":
            return web.json_response(
                {"success": False, "error": "unauthorized"}, status=401
            )
        return await handler(request)

    return middleware
