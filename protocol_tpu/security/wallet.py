"""Wallet: keypair identity with a derived on-ledger address.

Reference counterpart: crates/shared/src/web3/wallet.rs:28-68 (alloy
PrivateKeySigner, secp256k1 ECDSA + keccak addresses). Two schemes share
one wire format and one verifier here:

- :class:`Wallet` (default): Ed25519, address =
  ``0x + sha256(pubkey)[:20].hex()`` — the TPU-substrate native scheme.
- :class:`EvmWallet`: secp256k1 ECDSA over ``keccak256(message)``,
  address = ``0x + keccak256(uncompressed_pubkey[1:])[-20:].hex()`` —
  bit-identical to Ethereum address derivation, so this identity can sign
  for / be credited at a real EVM address.

Signatures travel as ``<pubkey_hex>:<sig_hex>``; :func:`verify_signature`
dispatches on the embedded pubkey's length (32 bytes = Ed25519, 65 bytes
= uncompressed secp256k1), checks the pubkey hashes to the claimed
address, then verifies — the same trust-nothing property ECDSA recovery
gives, without needing a recovery id on the wire. Every consumer
(signer, middleware, ledger invites) is scheme-agnostic through this one
seam, which is the adapter point for real-chain interop.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives.asymmetric.utils import (
    Prehashed,
    decode_dss_signature,
    encode_dss_signature,
)
from cryptography.exceptions import InvalidSignature


def _address_from_pubkey(pub_bytes: bytes) -> str:
    return "0x" + hashlib.sha256(pub_bytes).digest()[:20].hex()


# ---------------------------------------------------------------------------
# keccak-256 (the ORIGINAL Keccak padding, 0x01 — NOT sha3-256's 0x06, which
# is why hashlib can't provide it). Pure Python; only hashes short control
# messages, so throughput is irrelevant.
# ---------------------------------------------------------------------------

_MASK64 = (1 << 64) - 1
_KECCAK_RC = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)
_KECCAK_ROT = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)


def _rol64(v: int, n: int) -> int:
    if n == 0:
        return v
    return ((v << n) | (v >> (64 - n))) & _MASK64


def _keccak_f(a: list[list[int]]) -> list[list[int]]:
    for rc in _KECCAK_RC:
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol64(c[(x + 1) % 5], 1) for x in range(5)]
        a = [[a[x][y] ^ d[x] for y in range(5)] for x in range(5)]
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol64(a[x][y], _KECCAK_ROT[x][y])
        a = [
            [b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y])
             for y in range(5)]
            for x in range(5)
        ]
        a[0][0] ^= rc
    return a


def keccak256(data: bytes) -> bytes:
    rate = 136  # 1600 - 2*256 bits
    p = bytearray(data)
    pad = rate - (len(p) % rate)
    if pad == 1:
        p += b"\x81"
    else:
        p += b"\x01" + b"\x00" * (pad - 2) + b"\x80"
    a = [[0] * 5 for _ in range(5)]
    for off in range(0, len(p), rate):
        for i in range(rate // 8):
            a[i % 5][i // 5] ^= int.from_bytes(
                p[off + 8 * i: off + 8 * i + 8], "little"
            )
        a = _keccak_f(a)
    return b"".join(a[i % 5][i // 5].to_bytes(8, "little") for i in range(4))


def _evm_address(uncompressed_pubkey: bytes) -> str:
    """Ethereum address: last 20 bytes of keccak256 over the 64-byte
    public-key coordinates (the leading 0x04 SEC1 tag is dropped)."""
    return "0x" + keccak256(uncompressed_pubkey[1:]).hex()[-40:]


class Wallet:
    def __init__(self, private_key: Optional[Ed25519PrivateKey] = None):
        self._key = private_key or Ed25519PrivateKey.generate()
        self._pub_bytes = self._key.public_key().public_bytes_raw()
        self.address = _address_from_pubkey(self._pub_bytes)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Wallet":
        """Deterministic wallet from a 32-byte seed (dev/test fixtures)."""
        if len(seed) != 32:
            seed = hashlib.sha256(seed).digest()
        return cls(Ed25519PrivateKey.from_private_bytes(seed))

    @classmethod
    def from_hex(cls, hex_key: str) -> "Wallet":
        return cls(Ed25519PrivateKey.from_private_bytes(bytes.fromhex(hex_key.removeprefix("0x"))))

    def private_key_hex(self) -> str:
        return self._key.private_bytes_raw().hex()

    def sign_message(self, message: bytes | str) -> str:
        """Returns '<pubkey_hex>:<sig_hex>'."""
        if isinstance(message, str):
            message = message.encode()
        sig = self._key.sign(message)
        return f"{self._pub_bytes.hex()}:{sig.hex()}"


_SECP = ec.SECP256K1()
_SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
# cryptography's Prehashed only length-checks the digest (32 bytes), so it
# signs/verifies a keccak digest fine under the SHA256 label
_PREHASHED32 = ec.ECDSA(Prehashed(hashes.SHA256()))

# The pure-Python keccak runs ~8 s/MB: an unauthenticated party must not be
# able to buy that much verifier CPU. Control-plane messages (endpoint +
# timestamp + canonical JSON) are far below this; larger payloads travel
# the signed-URL artifact path, never the signed-JSON plane.
EVM_MAX_MESSAGE_BYTES = 64 * 1024

# ---------------------------------------------------------------------------
# secp256k1 group math for ECDSA public-key RECOVERY — the reference's wire
# carries a 65-byte r||s||v signature and derives the signer by recovery
# (alloy recover_address_from_msg, auth_signature_middleware.rs:386), with
# the EIP-191 personal-message digest. `cryptography` exposes no recovery,
# so the few group operations live here (Jacobian coordinates, one field
# inverse per recovery; ~ms per verify — control-plane rates, off the event
# loop, and size-capped like every keccak path).
# ---------------------------------------------------------------------------

_FP = 2**256 - 2**32 - 977  # secp256k1 field prime
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _jac_double(p):
    x, y, z = p
    if y == 0:
        return (0, 1, 0)
    s = (4 * x * y * y) % _FP
    m = (3 * x * x) % _FP  # a = 0 for secp256k1
    x2 = (m * m - 2 * s) % _FP
    y2 = (m * (s - x2) - 8 * pow(y, 4, _FP)) % _FP
    z2 = (2 * y * z) % _FP
    return (x2, y2, z2)


def _jac_add(p, q):
    if p[2] == 0:
        return q
    if q[2] == 0:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1s, z2s = (z1 * z1) % _FP, (z2 * z2) % _FP
    u1, u2 = (x1 * z2s) % _FP, (x2 * z1s) % _FP
    s1, s2 = (y1 * z2s * z2) % _FP, (y2 * z1s * z1) % _FP
    if u1 == u2:
        if s1 != s2:
            return (0, 1, 0)  # inverse points
        return _jac_double(p)
    h = (u2 - u1) % _FP
    r = (s2 - s1) % _FP
    h2 = (h * h) % _FP
    h3 = (h2 * h) % _FP
    x3 = (r * r - h3 - 2 * u1 * h2) % _FP
    y3 = (r * (u1 * h2 - x3) - s1 * h3) % _FP
    z3 = (h * z1 * z2) % _FP
    return (x3, y3, z3)


def _jac_mul(k, point_affine):
    acc = (0, 1, 0)
    add = (point_affine[0], point_affine[1], 1)
    while k:
        if k & 1:
            acc = _jac_add(acc, add)
        add = _jac_double(add)
        k >>= 1
    return acc


def _jac_to_affine(p):
    if p[2] == 0:
        return None
    zinv = pow(p[2], _FP - 2, _FP)
    zinv2 = (zinv * zinv) % _FP
    return ((p[0] * zinv2) % _FP, (p[1] * zinv2 * zinv) % _FP)


def ecrecover(digest: bytes, r: int, s: int, v: int) -> Optional[bytes]:
    """Recover the uncompressed secp256k1 public key (65 bytes) from an
    ECDSA signature over ``digest``; v is the recovery id (0/1, or the
    Ethereum 27/28 form). Returns None for any invalid input."""
    if v >= 27:
        v -= 27
    if v not in (0, 1) or not (1 <= r < _SECP_N and 1 <= s < _SECP_N):
        return None
    if len(digest) != 32:
        return None
    # R: the curve point whose x-coordinate is r (the r + n overflow case
    # requires x in [n, p), a ~2^-128 sliver — rejected, as most verifiers do)
    x = r
    y_sq = (pow(x, 3, _FP) + 7) % _FP
    y = pow(y_sq, (_FP + 1) // 4, _FP)
    if (y * y) % _FP != y_sq:
        return None
    if y % 2 != v:
        y = _FP - y
    z = int.from_bytes(digest, "big")
    rinv = pow(r, _SECP_N - 2, _SECP_N)
    # Q = r^-1 * (s*R - z*G); _jac_mul takes an AFFINE base point, so the
    # inner sum is normalized before the final scalar multiply
    sR = _jac_mul(s, (x, y))
    zG = _jac_mul(z, (_GX, _GY))
    neg_zG = (zG[0], (-zG[1]) % _FP, zG[2])
    inner = _jac_to_affine(_jac_add(sR, neg_zG))
    if inner is None:
        return None
    q = _jac_to_affine(_jac_mul(rinv, inner))
    if q is None:
        return None
    return b"\x04" + q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")


def eip191_digest(message: bytes) -> bytes:
    """keccak256 of the EIP-191 personal-message envelope — what
    alloy/MetaMask ``sign_message`` actually signs."""
    prefix = b"\x19Ethereum Signed Message:\n" + str(len(message)).encode()
    return keccak256(prefix + message)


class EvmWallet:
    """secp256k1/keccak wallet — the reference's exact signing scheme
    (crates/shared/src/web3/wallet.rs:28-68), producing REAL Ethereum
    addresses. Drop-in for :class:`Wallet` everywhere (same duck-type,
    same wire format); recovery is replaced by the embedded 65-byte
    uncompressed pubkey, which the verifier hashes back to the address."""

    def __init__(self, private_key: Optional[ec.EllipticCurvePrivateKey] = None):
        self._key = private_key or ec.generate_private_key(_SECP)
        pub = self._key.public_key().public_numbers()
        self._pub_bytes = (
            b"\x04" + pub.x.to_bytes(32, "big") + pub.y.to_bytes(32, "big")
        )
        self.address = _evm_address(self._pub_bytes)

    @classmethod
    def from_seed(cls, seed: bytes) -> "EvmWallet":
        """Deterministic wallet from a seed (dev/test fixtures)."""
        d = int.from_bytes(hashlib.sha256(seed).digest(), "big") % (_SECP_N - 1) + 1
        return cls(ec.derive_private_key(d, _SECP))

    @classmethod
    def from_hex(cls, hex_key: str) -> "EvmWallet":
        d = int(hex_key.removeprefix("0x"), 16)
        return cls(ec.derive_private_key(d, _SECP))

    def private_key_hex(self) -> str:
        return format(self._key.private_numbers().private_value, "064x")

    def sign_message(self, message: bytes | str) -> str:
        """Returns '<uncompressed_pubkey_hex>:<r||s hex>' over
        keccak256(message), with s normalized to the low half-order
        (EIP-2): a high-s twin of a valid signature is itself valid ECDSA,
        which would let an attacker mint a second wire-distinct signature
        for a captured request — and real Ethereum nodes reject high-s."""
        if isinstance(message, str):
            message = message.encode()
        if len(message) > EVM_MAX_MESSAGE_BYTES:
            raise ValueError(
                f"message of {len(message)} bytes exceeds the "
                f"{EVM_MAX_MESSAGE_BYTES}-byte keccak signing cap"
            )
        der = self._key.sign(keccak256(message), _PREHASHED32)
        r, s = decode_dss_signature(der)
        if s > _SECP_N // 2:
            s = _SECP_N - s
        sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
        return f"{self._pub_bytes.hex()}:{sig.hex()}"

    def sign_message_eth(self, message: bytes | str) -> str:
        """The reference's EXACT wire: ``0x`` + 65-byte r||s||v over the
        EIP-191 personal-message digest (what alloy's ``sign_message``
        emits, request_signer.rs:55-63) — verifiable by any Ethereum
        tool, and by :func:`verify_signature` via public-key recovery."""
        if isinstance(message, str):
            message = message.encode()
        if len(message) > EVM_MAX_MESSAGE_BYTES:
            raise ValueError(
                f"message of {len(message)} bytes exceeds the "
                f"{EVM_MAX_MESSAGE_BYTES}-byte keccak signing cap"
            )
        digest = eip191_digest(message)
        der = self._key.sign(digest, _PREHASHED32)
        r, s = decode_dss_signature(der)
        if s > _SECP_N // 2:
            s = _SECP_N - s
        # recovery id: the v whose recovered key is ours
        v = None
        for cand in (0, 1):
            if ecrecover(digest, r, s, cand) == self._pub_bytes:
                v = cand
                break
        if v is None:  # unreachable for a signature we just made
            raise ValueError("could not derive recovery id")
        sig = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([27 + v])
        return "0x" + sig.hex()


class EvmRecoveryWallet(EvmWallet):
    """An :class:`EvmWallet` whose DEFAULT wire is the reference's
    recovery format (``0x`` + r||s||v over the EIP-191 digest) — i.e.
    exactly what an alloy or MetaMask client sends
    (request_signer.rs:55-63). Dropping this into the signer/middleware
    suites proves the whole control plane authenticates reference-format
    clients verbatim."""

    def sign_message(self, message: bytes | str) -> str:
        return self.sign_message_eth(message)


def verify_signature(message: bytes | str, signature: str, expected_address: str) -> bool:
    """Checks the signature verifies AND its embedded pubkey hashes to the
    claimed address (the recovery-equivalent step). Scheme is dispatched on
    the pubkey length: 32 bytes = Ed25519, 65 bytes = secp256k1/keccak."""
    if isinstance(message, str):
        message = message.encode()
    if ":" not in signature:
        # the reference's recovery wire: 0x + 65-byte r||s||v over the
        # EIP-191 digest (auth_signature_middleware.rs:386 recovers the
        # address instead of carrying a pubkey) — signatures from real
        # Ethereum wallets verify here verbatim. STRICT canonical form
        # only (mandatory 0x, lowercase hex, v in {27,28}): every
        # accepted signature must have exactly one wire encoding, or a
        # re-encoded capture (uppercased hex, v rewritten 27->0) would
        # slip past the middleware's signature-string replay cache
        if not signature.startswith("0x") or signature != signature.lower():
            return False
        try:
            raw = bytes.fromhex(signature[2:])
        except ValueError:
            return False
        if len(raw) != 65 or len(message) > EVM_MAX_MESSAGE_BYTES:
            return False
        if raw[64] not in (27, 28):
            return False
        s_int = int.from_bytes(raw[32:64], "big")
        # low-s only (EIP-2): the high-s twin is an equally-valid ECDSA
        # signature with a DIFFERENT wire encoding, which would defeat
        # signature-keyed replay caches; alloy emits low-s, nothing legit
        # is lost
        if s_int > _SECP_N // 2:
            return False
        pub = ecrecover(
            eip191_digest(message),
            int.from_bytes(raw[:32], "big"),
            s_int,
            raw[64],
        )
        return pub is not None and _evm_address(pub) == expected_address.lower()
    try:
        pub_hex, sig_hex = signature.split(":", 1)
        pub_bytes = bytes.fromhex(pub_hex)
        sig = bytes.fromhex(sig_hex)
    except ValueError:
        return False
    if len(pub_bytes) == 65 and pub_bytes[0] == 4 and len(sig) == 64:
        # the pure-Python keccak is ~8 s/MB: refuse to hash attacker-sized
        # messages (the signer enforces the same cap)
        if len(message) > EVM_MAX_MESSAGE_BYTES:
            return False
        if _evm_address(pub_bytes) != expected_address.lower():
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        # reject the malleable high-s twin (EIP-2): otherwise one captured
        # request yields a second wire-distinct valid signature, defeating
        # any signature-keyed replay cache
        if r == 0 or s == 0 or s > _SECP_N // 2:
            return False
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(_SECP, pub_bytes)
            pub.verify(
                encode_dss_signature(r, s), keccak256(message), _PREHASHED32
            )
            return True
        except (InvalidSignature, ValueError):
            return False
    if _address_from_pubkey(pub_bytes) != expected_address.lower():
        return False
    try:
        Ed25519PublicKey.from_public_bytes(pub_bytes).verify(sig, message)
        return True
    except (InvalidSignature, ValueError):
        return False
