"""Wallet: Ed25519 keypair with a derived on-ledger address.

Reference counterpart: crates/shared/src/web3/wallet.rs (alloy
PrivateKeySigner). Deviation, by design: the reference uses secp256k1
ECDSA with address recovery; here identity is an Ed25519 keypair and the
address is ``0x + sha256(pubkey)[:20].hex()``. Signatures travel as
``<pubkey_hex>:<sig_hex>`` so any verifier can (a) check the pubkey hashes
to the claimed address and (b) verify the signature — the same
trust-nothing property recovery gives, without secp dependencies.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.exceptions import InvalidSignature


def _address_from_pubkey(pub_bytes: bytes) -> str:
    return "0x" + hashlib.sha256(pub_bytes).digest()[:20].hex()


class Wallet:
    def __init__(self, private_key: Optional[Ed25519PrivateKey] = None):
        self._key = private_key or Ed25519PrivateKey.generate()
        self._pub_bytes = self._key.public_key().public_bytes_raw()
        self.address = _address_from_pubkey(self._pub_bytes)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Wallet":
        """Deterministic wallet from a 32-byte seed (dev/test fixtures)."""
        if len(seed) != 32:
            seed = hashlib.sha256(seed).digest()
        return cls(Ed25519PrivateKey.from_private_bytes(seed))

    @classmethod
    def from_hex(cls, hex_key: str) -> "Wallet":
        return cls(Ed25519PrivateKey.from_private_bytes(bytes.fromhex(hex_key.removeprefix("0x"))))

    def private_key_hex(self) -> str:
        return self._key.private_bytes_raw().hex()

    def sign_message(self, message: bytes | str) -> str:
        """Returns '<pubkey_hex>:<sig_hex>'."""
        if isinstance(message, str):
            message = message.encode()
        sig = self._key.sign(message)
        return f"{self._pub_bytes.hex()}:{sig.hex()}"


def verify_signature(message: bytes | str, signature: str, expected_address: str) -> bool:
    """Checks the signature verifies AND its embedded pubkey hashes to the
    claimed address (the recovery-equivalent step)."""
    if isinstance(message, str):
        message = message.encode()
    try:
        pub_hex, sig_hex = signature.split(":", 1)
        pub_bytes = bytes.fromhex(pub_hex)
        sig = bytes.fromhex(sig_hex)
    except ValueError:
        return False
    if _address_from_pubkey(pub_bytes) != expected_address.lower():
        return False
    try:
        Ed25519PublicKey.from_public_bytes(pub_bytes).verify(sig, message)
        return True
    except (InvalidSignature, ValueError):
        return False
