"""In-process ledger implementing the reference's contract-wrapper surface.

Operation map (reference wrapper -> method here), from
crates/shared/src/web3/contracts/implementations/:

  AIToken             balance_of / mint / approve / transfer
  PrimeNetwork        register_provider / stake / add_compute_node /
                      validate_node / whitelist_provider / invalidate_work /
                      soft_invalidate_work / create_domain
  ComputeRegistry     get_provider / get_node / get_provider_total_compute
  ComputePool         create_pool / get_pool_info / start_pool /
                      is_node_in_pool / join_compute_pool (orchestrator-
                      signed invite verified against the pool's compute
                      manager key) / eject_node / blacklist_node /
                      submit_work
  StakeManager        get_stake / calculate_stake / slash
  DomainRegistry      get_domain
  SyntheticDataWorkValidator  get_work_keys / get_work_info / get_work_since
  RewardsDistributor  rewards accounting per submitted work unit

Invites: the reference binds a pool join to
keccak(domain, pool, node, nonce, expiration) signed by the pool's
compute-manager key (orchestrator/src/node/invite.rs:86-115; verified
worker-side at worker/src/p2p/mod.rs:396-497). Here the invite digest is
sha256 over the same canonical fields and the signature is the wallet
scheme from protocol_tpu.security.

Thread-safe; deterministic; state is plain dicts so a dev "devnet" is just
``Ledger()``.
"""

from __future__ import annotations

import enum
import hashlib
import time
from dataclasses import dataclass, field
from typing import Optional

from protocol_tpu.security.wallet import verify_signature
from protocol_tpu.utils.lockwitness import make_rlock


class LedgerError(Exception):
    pass


class PoolStatus(str, enum.Enum):
    PENDING = "PENDING"
    ACTIVE = "ACTIVE"
    COMPLETED = "COMPLETED"


@dataclass
class ProviderInfo:
    address: str
    stake: int = 0
    whitelisted: bool = False
    nodes: list[str] = field(default_factory=list)


@dataclass
class NodeInfo:
    address: str
    provider: str
    validated: bool = False
    active_pool: Optional[int] = None
    compute_units: int = 1


@dataclass
class PoolInfo:
    pool_id: int
    domain_id: int
    creator: str
    compute_manager_key: str
    pool_data_uri: str = ""  # carries the ComputeRequirements DSL
    status: PoolStatus = PoolStatus.PENDING
    nodes: list[str] = field(default_factory=list)
    blacklist: set[str] = field(default_factory=set)


@dataclass
class WorkInfo:
    pool_id: int
    node: str
    provider: str
    work_key: str
    work_units: int
    timestamp: float
    invalidated: bool = False
    soft_invalidated: bool = False


@dataclass
class DomainInfo:
    domain_id: int
    name: str
    validation_logic: str = ""


def invite_digest(domain_id: int, pool_id: int, node: str, nonce: str, expiration: float) -> bytes:
    msg = f"invite|{domain_id}|{pool_id}|{node.lower()}|{nonce}|{int(expiration)}"
    return hashlib.sha256(msg.encode()).digest()


class Ledger:
    def __init__(self, min_stake_per_compute_unit: int = 10):
        self._lock = make_rlock("ledger")
        self.balances: dict[str, int] = {}
        self.allowances: dict[tuple[str, str], int] = {}
        self.providers: dict[str, ProviderInfo] = {}
        self.nodes: dict[str, NodeInfo] = {}
        self.pools: dict[int, PoolInfo] = {}
        self.domains: dict[int, DomainInfo] = {}
        self.work: dict[tuple[int, str], WorkInfo] = {}  # (pool, work_key)
        self.rewards: dict[str, int] = {}
        self.validator_roles: set[str] = set()
        self.min_stake_per_compute_unit = min_stake_per_compute_unit
        self._next_pool_id = 0
        self._next_domain_id = 0

    # ------------- AIToken -------------

    def balance_of(self, address: str) -> int:
        return self.balances.get(address.lower(), 0)

    def mint(self, address: str, amount: int) -> None:
        with self._lock:
            self.balances[address.lower()] = self.balance_of(address) + amount

    def transfer(self, sender: str, to: str, amount: int) -> None:
        with self._lock:
            if self.balance_of(sender) < amount:
                raise LedgerError("insufficient balance")
            self.balances[sender.lower()] = self.balance_of(sender) - amount
            self.balances[to.lower()] = self.balance_of(to) + amount

    def approve(self, owner: str, spender: str, amount: int) -> None:
        with self._lock:
            self.allowances[(owner.lower(), spender.lower())] = amount

    # ------------- DomainRegistry / PrimeNetwork -------------

    def create_domain(self, name: str, validation_logic: str = "") -> int:
        with self._lock:
            did = self._next_domain_id
            self._next_domain_id += 1
            self.domains[did] = DomainInfo(did, name, validation_logic)
            return did

    def get_domain(self, domain_id: int) -> DomainInfo:
        info = self.domains.get(domain_id)
        if info is None:
            raise LedgerError(f"unknown domain {domain_id}")
        return info

    # ------------- provider registry -------------

    def calculate_stake(self, compute_units: int = 1) -> int:
        return self.min_stake_per_compute_unit * max(compute_units, 1)

    def register_provider(self, provider: str, stake: int) -> None:
        with self._lock:
            provider = provider.lower()
            if provider in self.providers:
                raise LedgerError("provider already registered")
            if self.balance_of(provider) < stake:
                raise LedgerError("insufficient balance for stake")
            if stake < self.calculate_stake(1):
                raise LedgerError("stake below minimum")
            self.balances[provider] -= stake
            self.providers[provider] = ProviderInfo(address=provider, stake=stake)

    def provider_exists(self, provider: str) -> bool:
        return provider.lower() in self.providers

    def get_provider(self, provider: str) -> ProviderInfo:
        info = self.providers.get(provider.lower())
        if info is None:
            raise LedgerError(f"unknown provider {provider}")
        return info

    def increase_stake(self, provider: str, amount: int) -> None:
        with self._lock:
            info = self.get_provider(provider)
            if self.balance_of(provider) < amount:
                raise LedgerError("insufficient balance")
            self.balances[provider.lower()] -= amount
            info.stake += amount

    def reclaim_stake(self, provider: str, amount: int) -> None:
        with self._lock:
            info = self.get_provider(provider)
            required = self.calculate_stake(
                sum(self.nodes[n].compute_units for n in info.nodes)
            )
            if info.stake - amount < required:
                raise LedgerError("cannot reclaim below required stake")
            info.stake -= amount
            self.balances[provider.lower()] = self.balance_of(provider) + amount

    def get_stake(self, provider: str) -> int:
        info = self.providers.get(provider.lower())
        return info.stake if info else 0

    def whitelist_provider(self, provider: str) -> None:
        with self._lock:
            self.get_provider(provider).whitelisted = True

    def is_provider_whitelisted(self, provider: str) -> bool:
        info = self.providers.get(provider.lower())
        return bool(info and info.whitelisted)

    # ------------- compute registry -------------

    def add_compute_node(
        self, provider: str, node: str, compute_units: int = 1
    ) -> None:
        with self._lock:
            info = self.get_provider(provider)
            node = node.lower()
            if node in self.nodes:
                raise LedgerError("node already registered")
            total_units = sum(self.nodes[n].compute_units for n in info.nodes)
            required = self.calculate_stake(total_units + compute_units)
            if info.stake < required:
                raise LedgerError("insufficient stake for node")
            self.nodes[node] = NodeInfo(
                address=node, provider=provider.lower(), compute_units=compute_units
            )
            info.nodes.append(node)

    def node_exists(self, node: str) -> bool:
        return node.lower() in self.nodes

    def get_node(self, node: str) -> NodeInfo:
        info = self.nodes.get(node.lower())
        if info is None:
            raise LedgerError(f"unknown node {node}")
        return info

    def remove_compute_node(self, provider: str, node: str) -> None:
        with self._lock:
            pinfo = self.get_provider(provider)
            ninfo = self.get_node(node)
            if ninfo.provider != provider.lower():
                raise LedgerError("node does not belong to provider")
            if ninfo.active_pool is not None:
                raise LedgerError("node is in a pool")
            del self.nodes[node.lower()]
            pinfo.nodes.remove(node.lower())

    # ------------- snapshot / restore -------------
    #
    # The reference's chain is durable by nature (reth devnet keeps state
    # across orchestrator restarts). The in-process dev ledger gets the
    # same property via explicit JSON snapshots, so a devnet --state-dir
    # restart restores the ECONOMIC state coherently with the services'
    # AOF journals (a surviving store against a wiped chain would strand
    # every worker as Unhealthy/not-in-pool).

    def snapshot(self, path: str) -> None:
        import dataclasses
        import json as _json
        import os as _os

        def enc(v):
            if dataclasses.is_dataclass(v):
                return {k: enc(x) for k, x in dataclasses.asdict(v).items()}
            if isinstance(v, enum.Enum):
                return v.value
            if isinstance(v, set):
                return sorted(v)
            if isinstance(v, dict):
                return {str(k): enc(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [enc(x) for x in v]
            return v

        with self._lock:
            # every collection is COPIED under the lock; json.dump then
            # runs outside it against a consistent frozen view
            state = {
                "balances": dict(self.balances),
                "allowances": {f"{a}|{b}": v for (a, b), v in self.allowances.items()},
                "providers": {k: enc(v) for k, v in self.providers.items()},
                "nodes": {k: enc(v) for k, v in self.nodes.items()},
                "pools": {str(k): enc(v) for k, v in self.pools.items()},
                "domains": {str(k): enc(v) for k, v in self.domains.items()},
                "work": {f"{p}|{w}": enc(v) for (p, w), v in self.work.items()},
                "rewards": dict(self.rewards),
                "validator_roles": sorted(self.validator_roles),
                "next_pool_id": self._next_pool_id,
                "next_domain_id": self._next_domain_id,
                "min_stake_per_compute_unit": self.min_stake_per_compute_unit,
            }
        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(state, f)
        _os.replace(tmp, path)

    @classmethod
    def open(cls, path: Optional[str], **kwargs) -> "Ledger":
        """Restore from ``path`` when it exists, else a fresh ledger —
        the one entry point devnet and the ledger-api pod share."""
        import os as _os

        if path and _os.path.exists(path):
            return cls.restore(path, **kwargs)
        return cls(**kwargs)

    def try_snapshot(self, path: str) -> bool:
        """Snapshot with visible failure (a silently-stale ledger.json
        restores an incoherent chain later)."""
        try:
            self.snapshot(path)
            return True
        except Exception as e:
            import sys as _sys

            print(f"ledger snapshot failed: {e}", file=_sys.stderr)
            return False

    @classmethod
    def restore(cls, path: str, **kwargs) -> "Ledger":
        import json as _json

        with open(path) as f:
            s = _json.load(f)
        # the persisted economics win unless explicitly overridden
        kwargs.setdefault(
            "min_stake_per_compute_unit",
            s.get("min_stake_per_compute_unit", 10),
        )
        led = cls(**kwargs)
        led.balances = dict(s["balances"])
        led.allowances = {
            tuple(k.split("|", 1)): v for k, v in s["allowances"].items()
        }
        led.providers = {
            k: ProviderInfo(**v) for k, v in s["providers"].items()
        }
        led.nodes = {k: NodeInfo(**v) for k, v in s["nodes"].items()}
        for k, v in s["pools"].items():
            v = dict(v)
            v["status"] = PoolStatus(v["status"])
            v["blacklist"] = set(v["blacklist"])
            led.pools[int(k)] = PoolInfo(**v)
        led.domains = {
            int(k): DomainInfo(**v) for k, v in s["domains"].items()
        }
        for k, v in s["work"].items():
            pool_s, work_key = k.split("|", 1)
            led.work[(int(pool_s), work_key)] = WorkInfo(**v)
        led.rewards = dict(s["rewards"])
        led.validator_roles = set(s["validator_roles"])
        led._next_pool_id = s["next_pool_id"]
        led._next_domain_id = s["next_domain_id"]
        return led

    def grant_validator_role(self, address: str) -> None:
        """Register a validator wallet on the substrate (reference
        prime_network.get_validator_role surface; workers derive their
        control-plane allowlist from this set, cli/command.rs:717-734)."""
        with self._lock:
            self.validator_roles.add(address.lower())

    def revoke_validator_role(self, address: str) -> None:
        with self._lock:
            self.validator_roles.discard(address.lower())

    def get_validator_role(self) -> list[str]:
        with self._lock:
            return sorted(self.validator_roles)

    def validate_node(self, node: str) -> None:
        """Validator attests hardware (reference
        prime_network.validate_node)."""
        with self._lock:
            self.get_node(node).validated = True

    def is_node_validated(self, node: str) -> bool:
        info = self.nodes.get(node.lower())
        return bool(info and info.validated)

    def get_provider_total_compute(self, provider: str) -> int:
        info = self.providers.get(provider.lower())
        if not info:
            return 0
        return sum(self.nodes[n].compute_units for n in info.nodes)

    # ------------- compute pool -------------

    def create_pool(
        self,
        domain_id: int,
        creator: str,
        compute_manager_key: str,
        pool_data_uri: str = "",
    ) -> int:
        with self._lock:
            self.get_domain(domain_id)
            pid = self._next_pool_id
            self._next_pool_id += 1
            self.pools[pid] = PoolInfo(
                pool_id=pid,
                domain_id=domain_id,
                creator=creator.lower(),
                compute_manager_key=compute_manager_key.lower(),
                pool_data_uri=pool_data_uri,
            )
            return pid

    def get_pool_info(self, pool_id: int) -> PoolInfo:
        info = self.pools.get(pool_id)
        if info is None:
            raise LedgerError(f"unknown pool {pool_id}")
        return info

    def start_pool(self, pool_id: int, caller: str) -> None:
        with self._lock:
            pool = self.get_pool_info(pool_id)
            if caller.lower() != pool.creator:
                raise LedgerError("only creator can start pool")
            pool.status = PoolStatus.ACTIVE

    def join_compute_pool(
        self,
        pool_id: int,
        provider: str,
        node: str,
        nonce: str,
        expiration: float,
        invite_signature: str,
    ) -> None:
        """Node joins with an orchestrator-signed invite
        (invite.rs:86-115 + worker/p2p/mod.rs:453-468)."""
        with self._lock:
            pool = self.get_pool_info(pool_id)
            if pool.status != PoolStatus.ACTIVE:
                raise LedgerError("pool not active")
            node_l = node.lower()
            ninfo = self.get_node(node_l)
            if ninfo.provider != provider.lower():
                raise LedgerError("node does not belong to provider")
            if not ninfo.validated:
                raise LedgerError("node not validated")
            if node_l in pool.blacklist:
                raise LedgerError("node blacklisted")
            if ninfo.active_pool is not None:
                raise LedgerError("node already in a pool")
            if expiration < time.time():
                raise LedgerError("invite expired")
            digest = invite_digest(pool.domain_id, pool_id, node_l, nonce, expiration)
            if not verify_signature(digest, invite_signature, pool.compute_manager_key):
                raise LedgerError("invalid invite signature")
            pool.nodes.append(node_l)
            ninfo.active_pool = pool_id

    def is_node_in_pool(self, pool_id: int, node: str) -> bool:
        pool = self.pools.get(pool_id)
        return bool(pool and node.lower() in pool.nodes)

    def leave_compute_pool(self, pool_id: int, node: str) -> None:
        with self._lock:
            pool = self.get_pool_info(pool_id)
            node_l = node.lower()
            if node_l in pool.nodes:
                pool.nodes.remove(node_l)
            ninfo = self.nodes.get(node_l)
            if ninfo and ninfo.active_pool == pool_id:
                ninfo.active_pool = None

    def eject_node(self, pool_id: int, node: str, caller: str) -> None:
        with self._lock:
            pool = self.get_pool_info(pool_id)
            if caller.lower() not in (pool.creator, pool.compute_manager_key):
                raise LedgerError("not authorized to eject")
            self.leave_compute_pool(pool_id, node)

    def blacklist_node(self, pool_id: int, node: str, caller: str) -> None:
        with self._lock:
            pool = self.get_pool_info(pool_id)
            if caller.lower() not in (pool.creator, pool.compute_manager_key):
                raise LedgerError("not authorized to blacklist")
            pool.blacklist.add(node.lower())
            self.leave_compute_pool(pool_id, node)

    # ------------- work submission / validation -------------

    def submit_work(
        self, pool_id: int, node: str, work_key: str, work_units: int
    ) -> None:
        """submitWork(poolId, node, workKey=sha256, workUnits=flops)
        (worker/src/docker/taskbridge/file_handler.rs submission path)."""
        with self._lock:
            pool = self.get_pool_info(pool_id)
            node_l = node.lower()
            if node_l not in pool.nodes:
                raise LedgerError("node not in pool")
            key = (pool_id, work_key)
            if key in self.work:
                raise LedgerError("work key already submitted")
            self.work[key] = WorkInfo(
                pool_id=pool_id,
                node=node_l,
                provider=self.get_node(node_l).provider,
                work_key=work_key,
                work_units=work_units,
                timestamp=time.time(),
            )
            self.rewards[node_l] = self.rewards.get(node_l, 0) + work_units

    def get_work_keys(self, pool_id: int) -> list[str]:
        return [k for (pid, k) in self.work if pid == pool_id]

    def get_work_info(self, pool_id: int, work_key: str) -> Optional[WorkInfo]:
        return self.work.get((pool_id, work_key))

    def get_work_since(self, pool_id: int, since: float) -> list[WorkInfo]:
        return sorted(
            (
                w
                for (pid, _), w in self.work.items()
                if pid == pool_id and w.timestamp >= since
            ),
            key=lambda w: w.timestamp,
        )

    def invalidate_work(self, pool_id: int, work_key: str, penalty: int = 0) -> None:
        """Hard invalidation + stake slash (prime_network.invalidate_work)."""
        with self._lock:
            info = self.work.get((pool_id, work_key))
            if info is None:
                raise LedgerError("unknown work key")
            info.invalidated = True
            self.rewards[info.node] = max(
                0, self.rewards.get(info.node, 0) - info.work_units
            )
            if penalty:
                pinfo = self.providers.get(info.provider)
                if pinfo:
                    pinfo.stake = max(0, pinfo.stake - penalty)

    def soft_invalidate_work(self, pool_id: int, work_key: str) -> None:
        """Reward clawback without slashing (soft_invalidate_work)."""
        with self._lock:
            info = self.work.get((pool_id, work_key))
            if info is None:
                raise LedgerError("unknown work key")
            info.soft_invalidated = True
            self.rewards[info.node] = max(
                0, self.rewards.get(info.node, 0) - info.work_units
            )

    def get_rewards(self, node: str) -> int:
        return self.rewards.get(node.lower(), 0)
