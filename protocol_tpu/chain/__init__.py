"""Economic/consensus substrate: an in-process ledger.

The reference's economic layer is a set of Ethereum contracts (PrimeNetwork,
ComputeRegistry, ComputePool, StakeManager, AIToken, DomainRegistry,
SyntheticDataWorkValidator, RewardsDistributor) accessed through Rust
wrappers (crates/shared/src/web3/contracts/). The Solidity itself is an
EMPTY submodule in the reference (SURVEY.md §2.8), so this framework
provides the *operation surface those wrappers expose* as an in-process
ledger — the same API seam, swappable later for a real chain backend.
"""

from protocol_tpu.chain.ledger import (
    Ledger,
    LedgerError,
    PoolStatus,
    WorkInfo,
)

__all__ = ["Ledger", "LedgerError", "PoolStatus", "WorkInfo"]
