"""Ledger client over the ledger HTTP API.

The reference's services each hold alloy JSON-RPC contract wrappers against
the chain (crates/shared/src/web3/). Here, out-of-process services (the
Helm-deployed discovery/orchestrator/validator pods) hold a ``RemoteLedger``
speaking the LedgerApiService seam — same method surface as the in-process
``Ledger``, so every service constructor accepts either interchangeably.

Synchronous on purpose: ledger calls sit on control-plane paths that are
already synchronous (services call ``self.ledger.x(...)`` directly) and
volumes are tens of calls per loop tick; transport is the shared
per-thread keep-alive client (utils.http_client). Callers on the event
loop wrap service loops in ``asyncio.to_thread`` where latency matters.
"""

from __future__ import annotations

from typing import Optional

from protocol_tpu.utils.http_client import KeepAliveJsonClient

from .ledger import (
    DomainInfo,
    LedgerError,
    NodeInfo,
    PoolInfo,
    PoolStatus,
    ProviderInfo,
    WorkInfo,
)


class RemoteLedger:
    def __init__(
        self,
        base_url: str,
        admin_api_key: str = "",
        timeout: float = 10.0,
        max_tries: int = 3,
        retry_delay: float = 2.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.admin_api_key = admin_api_key
        self.timeout = timeout
        self.max_tries = max_tries
        self.retry_delay = retry_delay
        self._http = KeepAliveJsonClient(base_url, timeout, LedgerError)

    # ---- transport

    def _call(self, kind: str, op: str, params: dict):
        """Transport with the reference's retry_call semantics
        (crates/shared/src/web3/contracts/helpers/utils.rs:22-70): writes
        retry up to ``max_tries`` with a delay, and a per-call ``tx_id``
        makes the resend safe — if the earlier attempt actually landed
        but its response was lost, the ledger API replays the recorded
        outcome instead of double-applying (the receipt check's HTTP
        analog). Application errors (LedgerError from the ledger itself)
        never retry; only transport failures do."""
        import time as _time
        import uuid

        headers = {}
        write = kind == "write"
        if write and self.admin_api_key:
            headers["Authorization"] = f"Bearer {self.admin_api_key}"
        if write:
            params = {**params, "tx_id": uuid.uuid4().hex}
        tries = max(1, self.max_tries) if write else 1
        for attempt in range(tries):
            try:
                payload = self._http.post(
                    f"/ledger/{kind}/{op}",
                    params,
                    headers=headers,
                    # tx_id dedup makes write resends safe end-to-end
                    retry_response=True,
                )
                break
            except LedgerError:
                if attempt == tries - 1:
                    raise
                _time.sleep(self.retry_delay)
        if not payload.get("success"):
            raise LedgerError(payload.get("error", f"{op} failed"))
        return payload.get("data")

    def _read(self, op: str, **params):
        return self._call("read", op, params)

    def _write(self, op: str, **params):
        return self._call("write", op, params)

    # ---- AIToken

    def balance_of(self, address: str) -> int:
        return self._read("balance_of", address=address)

    def mint(self, address: str, amount: int) -> None:
        self._write("mint", address=address, amount=amount)

    def transfer(self, sender: str, to: str, amount: int) -> None:
        self._write("transfer", sender=sender, to=to, amount=amount)

    def approve(self, owner: str, spender: str, amount: int) -> None:
        self._write("approve", owner=owner, spender=spender, amount=amount)

    # ---- DomainRegistry / PrimeNetwork

    def create_domain(self, name: str, validation_logic: str = "") -> int:
        return self._write(
            "create_domain", name=name, validation_logic=validation_logic
        )

    def get_domain(self, domain_id: int) -> DomainInfo:
        return DomainInfo(**self._read("get_domain", domain_id=domain_id))

    def calculate_stake(self, compute_units: int) -> int:
        return self._read("calculate_stake", compute_units=compute_units)

    def register_provider(self, provider: str, stake: int) -> None:
        self._write("register_provider", provider=provider, stake=stake)

    def provider_exists(self, provider: str) -> bool:
        return self._read("provider_exists", provider=provider)

    def get_provider(self, provider: str) -> ProviderInfo:
        return ProviderInfo(**self._read("get_provider", provider=provider))

    def increase_stake(self, provider: str, amount: int) -> None:
        self._write("increase_stake", provider=provider, amount=amount)

    def reclaim_stake(self, provider: str, amount: int) -> None:
        self._write("reclaim_stake", provider=provider, amount=amount)

    def get_stake(self, provider: str) -> int:
        return self._read("get_stake", provider=provider)

    def whitelist_provider(self, provider: str) -> None:
        self._write("whitelist_provider", provider=provider)

    def is_provider_whitelisted(self, provider: str) -> bool:
        return self._read("is_provider_whitelisted", provider=provider)

    def add_compute_node(
        self, provider: str, node: str, compute_units: int = 1
    ) -> None:
        self._write(
            "add_compute_node",
            provider=provider,
            node=node,
            compute_units=compute_units,
        )

    def node_exists(self, node: str) -> bool:
        return self._read("node_exists", node=node)

    def get_node(self, node: str) -> NodeInfo:
        return NodeInfo(**self._read("get_node", node=node))

    def remove_compute_node(self, provider: str, node: str) -> None:
        self._write("remove_compute_node", provider=provider, node=node)

    def grant_validator_role(self, address: str) -> None:
        self._write("grant_validator_role", address=address)

    def revoke_validator_role(self, address: str) -> None:
        self._write("revoke_validator_role", address=address)

    def get_validator_role(self) -> list[str]:
        return self._read("get_validator_role")

    def validate_node(self, node: str) -> None:
        self._write("validate_node", node=node)

    def is_node_validated(self, node: str) -> bool:
        return self._read("is_node_validated", node=node)

    def get_provider_total_compute(self, provider: str) -> int:
        return self._read("get_provider_total_compute", provider=provider)

    # ---- ComputePool

    def create_pool(
        self,
        domain_id: int,
        creator: str,
        compute_manager_key: str,
        pool_data_uri: str = "",
    ) -> int:
        return self._write(
            "create_pool",
            domain_id=domain_id,
            creator=creator,
            compute_manager_key=compute_manager_key,
            pool_data_uri=pool_data_uri,
        )

    def get_pool_info(self, pool_id: int) -> PoolInfo:
        d = dict(self._read("get_pool_info", pool_id=pool_id))
        d["status"] = PoolStatus(d["status"])
        d["blacklist"] = set(d.get("blacklist", []))
        return PoolInfo(**d)

    def start_pool(self, pool_id: int, caller: str) -> None:
        self._write("start_pool", pool_id=pool_id, caller=caller)

    def join_compute_pool(
        self,
        pool_id: int,
        provider: str,
        node: str,
        nonce: str,
        expiration: float,
        invite_signature: str,
    ) -> None:
        self._write(
            "join_compute_pool",
            pool_id=pool_id,
            provider=provider,
            node=node,
            nonce=nonce,
            expiration=expiration,
            invite_signature=invite_signature,
        )

    def is_node_in_pool(self, pool_id: int, node: str) -> bool:
        return self._read("is_node_in_pool", pool_id=pool_id, node=node)

    def leave_compute_pool(self, pool_id: int, node: str) -> None:
        self._write("leave_compute_pool", pool_id=pool_id, node=node)

    def eject_node(self, pool_id: int, node: str, caller: str) -> None:
        self._write("eject_node", pool_id=pool_id, node=node, caller=caller)

    def blacklist_node(self, pool_id: int, node: str, caller: str) -> None:
        self._write("blacklist_node", pool_id=pool_id, node=node, caller=caller)

    # ---- work

    def submit_work(
        self, pool_id: int, node: str, work_key: str, work_units: int
    ) -> None:
        self._write(
            "submit_work",
            pool_id=pool_id,
            node=node,
            work_key=work_key,
            work_units=work_units,
        )

    def get_work_keys(self, pool_id: int) -> list[str]:
        return self._read("get_work_keys", pool_id=pool_id)

    def _work_info(self, d: Optional[dict]) -> Optional[WorkInfo]:
        return WorkInfo(**d) if d else None

    def get_work_info(self, pool_id: int, work_key: str) -> Optional[WorkInfo]:
        return self._work_info(
            self._read("get_work_info", pool_id=pool_id, work_key=work_key)
        )

    def get_work_since(self, pool_id: int, since: float) -> list[WorkInfo]:
        return [
            self._work_info(d)
            for d in self._read("get_work_since", pool_id=pool_id, since=since)
        ]

    def invalidate_work(
        self, pool_id: int, work_key: str, penalty: int = 0
    ) -> None:
        self._write(
            "invalidate_work", pool_id=pool_id, work_key=work_key, penalty=penalty
        )

    def soft_invalidate_work(self, pool_id: int, work_key: str) -> None:
        self._write("soft_invalidate_work", pool_id=pool_id, work_key=work_key)

    def get_rewards(self, node: str) -> int:
        return self._read("get_rewards", node=node)
