"""Devnet: the one-command local cluster.

The reference's ``make up`` boots a reth devnet + redis + contract deploy +
discovery/orchestrator/validator in tmux panes (Makefile:57-116,
docker-compose.yml). Here the whole stack is one asyncio process:

    python -m protocol_tpu.devnet [--workers N] [--requirements DSL]

Boots: ledger API (:8095), discovery (:8089), orchestrator (:8090),
validator (:8094), and N in-process workers with subprocess runtimes.
Prints admin credentials and example CLI invocations, then runs the loops
until interrupted.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

import aiohttp
from aiohttp import web

from protocol_tpu.chain import Ledger
from protocol_tpu.models.node import DiscoveryNode
from protocol_tpu.sched import Scheduler, TpuBatchMatcher
from protocol_tpu.sched.node_groups import NodeGroupConfiguration, NodeGroupsPlugin
from protocol_tpu.security import Wallet, sign_request
from protocol_tpu.services.discovery import DiscoveryService
from protocol_tpu.services.ledger_api import LedgerApiService
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.services.validator import ValidatorService
from protocol_tpu.services.worker import SubprocessRuntime, TaskBridge, WorkerAgent, detect_compute_specs
from protocol_tpu.store import StoreContext
from protocol_tpu.utils.storage import LocalDirStorageProvider


async def start_app(app: web.Application, port: int) -> web.AppRunner:
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return runner


async def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="protocol_tpu local devnet")
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument(
        "--runtime",
        choices=["subprocess", "docker"],
        default="subprocess",
        help="worker task runtime (docker mirrors the reference's container "
        "execution model; requires a docker CLI on PATH)",
    )
    parser.add_argument("--requirements", default="", help="pool requirements DSL")
    parser.add_argument("--admin-key", default="admin")
    parser.add_argument("--storage-dir", default="/tmp/protocol_tpu_storage")
    parser.add_argument(
        "--scheduler-backend",
        default="local",
        help="local | remote | remote:HOST:PORT — 'remote' routes the "
        "matcher's kernels through the gRPC scheduler backend seam "
        "(bare 'remote' boots an in-process backend)",
    )
    parser.add_argument(
        "--state-dir",
        default="",
        help="persist discovery/orchestrator state here (AOF journals); "
        "empty = volatile, as before",
    )
    parser.add_argument("--base-port", type=int, default=8089)
    parser.add_argument(
        "--group-configs",
        default="",
        help='JSON list of {"name","min_group_size","max_group_size","compute_requirements"}',
    )
    parser.add_argument("--oneshot", action="store_true", help="boot, print state, exit (smoke test)")
    parser.add_argument(
        "--probe-accelerator",
        action="store_true",
        help="include jax.devices() in worker hardware detection (can block "
        "if the accelerator plugin is unreachable)",
    )
    parser.add_argument(
        "--cpu",
        action="store_true",
        help="pin JAX to the host CPU backend (devnet without an accelerator)",
    )
    args = parser.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    dport, oport, vport, lport = (
        args.base_port,
        args.base_port + 1,
        args.base_port + 5,
        args.base_port + 6,
    )

    # ---- substrate
    creator = Wallet.from_seed(b"devnet-creator")
    manager = Wallet.from_seed(b"devnet-manager")
    validator_wallet = Wallet.from_seed(b"devnet-validator")
    ledger_path = (
        os.path.join(args.state_dir, "ledger.json") if args.state_dir else None
    )
    # the chain must survive restarts WITH the service stores, or the
    # restored pool strands every worker as not-in-pool (the reference
    # chain is durable by nature)
    ledger = Ledger.open(ledger_path)
    if ledger.pools:
        pid = min(ledger.pools)
        did = ledger.pools[pid].domain_id
        print(f"ledger restored from {ledger_path} (pool {pid})")
    else:
        did = ledger.create_domain("devnet", validation_logic="toploc")
        pid = ledger.create_pool(
            did, creator.address, manager.address, args.requirements
        )
        ledger.start_pool(pid, creator.address)
        if ledger_path:
            ledger.try_snapshot(ledger_path)

    session = aiohttp.ClientSession()
    runners = []

    # ---- ledger API
    ledger_api = LedgerApiService(ledger, admin_api_key=args.admin_key)
    runners.append(await start_app(ledger_api.make_app(), lport))

    # ---- discovery
    discovery = DiscoveryService(
        ledger,
        pid,
        admin_api_key=args.admin_key,
        persist_path=(
            os.path.join(args.state_dir, "discovery.aof") if args.state_dir else None
        ),
    )
    runners.append(await start_app(discovery.make_app(), dport))
    discovery_url = f"http://127.0.0.1:{dport}"

    # ---- orchestrator
    if args.state_dir:
        from protocol_tpu.store.kv import KVStore

        store = StoreContext(
            KVStore(persist_path=os.path.join(args.state_dir, "orchestrator.aof"))
        )
    else:
        store = StoreContext.new_test()
    groups_plugin = None
    if args.group_configs:
        configs = [
            NodeGroupConfiguration.from_dict(d) for d in json.loads(args.group_configs)
        ]
        groups_plugin = NodeGroupsPlugin(store, configs)
        groups_plugin.attach_observers()
    if args.scheduler_backend != "local" and not (
        args.scheduler_backend == "remote"
        or args.scheduler_backend.startswith("remote:")
    ):
        print(
            f"unknown --scheduler-backend {args.scheduler_backend!r} "
            "(want local | remote | remote:HOST:PORT)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    if args.scheduler_backend != "local":
        # control plane -> gRPC -> kernels (the north-star seam). A bare
        # "remote" boots an in-process backend; "remote:HOST:PORT"
        # points at an external one (e.g. the TPU node pool).
        from protocol_tpu.services import scheduler_grpc

        addr = args.scheduler_backend.partition(":")[2]
        grpc_server = None
        if not addr:
            addr = "127.0.0.1:50061"
            # hold the reference: a dropped grpc.Server is GC'd and stops
            grpc_server = scheduler_grpc.serve(addr)
        matcher = scheduler_grpc.RemoteBatchMatcher(
            store, addr,
            wire=os.environ.get("PROTOCOL_TPU_WIRE", "v2"),
        )
        matcher.grpc_server = grpc_server
    else:
        matcher = TpuBatchMatcher(store)
    matcher.attach_observers()
    if groups_plugin is not None:
        # composed gang scheduling: grouped nodes through the plugin
        # (matcher-ranked), ungrouped through the individual batch solve
        matcher.attach_groups(groups_plugin)
        scheduler = Scheduler(
            store, plugins=[groups_plugin], batch_matcher=matcher
        )
    else:
        scheduler = Scheduler(store, batch_matcher=matcher)

    async def discovery_fetcher():
        headers, _ = sign_request(f"/api/pool/{pid}", manager)
        async with session.get(
            f"{discovery_url}/api/pool/{pid}", headers=headers
        ) as resp:
            data = await resp.json()
            return [DiscoveryNode.from_dict(d) for d in data.get("data", [])]

    async def invite_sender(node, payload):
        url = (node.p2p_addresses or [None])[0]
        if not url:
            return False
        headers, body = sign_request("/control/invite", manager, payload)
        try:
            async with session.post(
                f"{url}/invite", json=body, headers=headers
            ) as resp:
                return resp.status == 200
        except aiohttp.ClientError:
            return False

    orchestrator = OrchestratorService(
        ledger,
        pid,
        manager,
        store=store,
        scheduler=scheduler,
        groups_plugin=groups_plugin,
        storage=LocalDirStorageProvider(
            args.storage_dir, public_base_url=f"http://127.0.0.1:{oport}"
        ),
        discovery_fetcher=discovery_fetcher,
        invite_sender=invite_sender,
        admin_api_key=args.admin_key,
        heartbeat_url=f"http://127.0.0.1:{oport}",
        control_http=session,
    )
    runners.append(await orchestrator.serve(port=oport))

    # ---- validator
    async def validator_fetcher():
        headers, _ = sign_request("/api/validator", validator_wallet)
        async with session.get(
            f"{discovery_url}/api/validator", headers=headers
        ) as resp:
            data = await resp.json()
            return [DiscoveryNode.from_dict(d) for d in data.get("data", [])]

    validator = ValidatorService(
        validator_wallet,
        ledger,
        pid,
        synthetic=None,  # attach a toploc server via TOPLOC_URL when present
        discovery_fetcher=validator_fetcher,
        http=session,
        challenge_size=64,
    )
    runners.append(await start_app(validator.make_app(), vport))

    async def validator_loop():
        while True:
            try:
                await validator.validation_loop_once()
            except Exception:
                pass
            await asyncio.sleep(5.0)  # validator/src/main.rs:33

    async def discovery_sync_loop():
        # ChainSync every 10 s (discovery/src/chainsync/sync.rs:16) +
        # location enrichment (location_enrichment.rs)
        while True:
            try:
                discovery.chain_sync_once()
                await discovery.enrich_locations_once()
            except Exception:
                pass
            if ledger_path:
                ledger.try_snapshot(ledger_path)
            await asyncio.sleep(10.0)

    loops = [
        asyncio.get_running_loop().create_task(validator_loop()),
        asyncio.get_running_loop().create_task(discovery_sync_loop()),
    ]

    # ---- workers
    workers = []
    specs, _report = detect_compute_specs(
        "/", probe_accelerator=args.probe_accelerator
    )
    for i in range(args.workers):
        provider = Wallet.from_seed(f"devnet-provider-{i}".encode())
        node = Wallet.from_seed(f"devnet-node-{i}".encode())
        ledger.mint(provider.address, 1_000_000)
        wport = args.base_port + 10 + i
        socket_path = f"/tmp/protocol_tpu_worker_{i}/bridge.sock"
        if args.runtime == "docker":
            from protocol_tpu.services.docker_runtime import DockerRuntime

            def runtime_factory(slot=None, sp=socket_path):
                return DockerRuntime(socket_path=sp, slot=slot)
        else:
            def runtime_factory(slot=None, sp=socket_path):
                return SubprocessRuntime(socket_path=sp)
        agent = WorkerAgent(
            provider_wallet=provider,
            node_wallet=node,
            ledger=ledger,
            pool_id=pid,
            runtime=runtime_factory(),
            compute_specs=specs,
            port=wport,
            http=session,
            known_orchestrators=[manager.address],
            known_validators=[validator_wallet.address],
            runtime_factory=runtime_factory,
        )
        agent.register_on_ledger()
        ledger.whitelist_provider(provider.address)  # devnet auto-onboards
        bridge = TaskBridge(socket_path, agent)
        await bridge.start()
        runners.append(await start_app(agent.make_control_app(), wport))
        await agent.upload_to_discovery([discovery_url])
        workers.append(agent)

        async def worker_loop(agent=agent):
            while True:
                try:
                    await agent.heartbeat_once()
                except Exception:
                    pass
                await asyncio.sleep(10.0)  # heartbeat interval (reference)

        loops.append(asyncio.get_running_loop().create_task(worker_loop()))

    print(f"devnet up: pool {pid} (domain {did})")
    print(f"  ledger api    http://127.0.0.1:{lport}   (admin key: {args.admin_key})")
    print(f"  discovery     {discovery_url}")
    print(f"  orchestrator  http://127.0.0.1:{oport}")
    print(f"  validator     http://127.0.0.1:{vport}")
    print(f"  workers       {len(workers)} in-process agents")
    print(f"  manager addr  {manager.address}")
    print("try:")
    print(
        f"  python -m protocol_tpu.cli --orchestrator http://127.0.0.1:{oport} "
        f"--api-key {args.admin_key} create-task --name hello --image demo "
        "--cmd 'echo,hello-from-${NODE_ADDRESS}'"
    )
    sys.stdout.flush()

    if args.oneshot:
        await asyncio.sleep(0.5)
        for t in loops:
            t.cancel()
        for r in runners:
            await r.cleanup()
        await session.close()
        return

    try:
        await asyncio.Event().wait()
    finally:
        for t in loops:
            t.cancel()
        for r in runners:
            await r.cleanup()
        await session.close()


if __name__ == "__main__":
    asyncio.run(main())
