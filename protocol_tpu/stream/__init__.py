"""Event-driven streaming assignment: sub-tick online repair with
certified bounded divergence.

The batch seam answers a churned marketplace once per tick; this
package answers each churn EVENT the moment it arrives — heartbeat,
join/leave, requirement churn — by localized repair over the warm
arena (O(churned rows) per event, never a full-matrix candidate pass),
with an incrementally-maintained certified optimality gap bounding how
far the streamed plan can drift from the batch plan, and a periodic
full-solve reconciliation that is bit-identical to a batch replay of
the same event trace. See ARCHITECTURE.md "Streaming assignment".
"""

from protocol_tpu.stream.engine import StreamEngine, StreamResult
from protocol_tpu.stream.events import (
    EVENT_KINDS,
    SourceDedup,
    StreamEvent,
    coalesce,
    event_from_delta,
)
from protocol_tpu.stream.quality import GapTracker
from protocol_tpu.stream.replay import batch_shadow_replay, stream_replay

__all__ = [
    "EVENT_KINDS",
    "GapTracker",
    "SourceDedup",
    "StreamEngine",
    "StreamEvent",
    "StreamResult",
    "batch_shadow_replay",
    "coalesce",
    "event_from_delta",
    "stream_replay",
]
