"""Deterministic event-stream replay + the batch shadow harness.

A *stream trace* is an ordinary PTTRACE1 file whose DELTA frames each
carry ONE churn event (rows + full-state values) with the stream meta
(``{kind, source, seq, at_us}``) in the frame's events list — the synth
factory (``trace.synth.synth_event_trace``) writes them, and
``stream_replay`` feeds them through a :class:`StreamEngine` event by
event:

  * outcomes recorded per EVENT (tick 0 = the priming cold solve), so
    replay verification localizes a divergence to the first EVENT, not
    the first batch tick;
  * ``chaos=`` runs the same trace through a seeded drop/dup/reorder
    delivery schedule (``faults.plan.event_delivery_order``) — dropped
    events are retransmitted later, duplicates and overtaken events hit
    the dedup ladder, and the FINAL reconciled plan must still be
    bit-identical to the fault-free replay's (the convergence gate);
  * ``batch_shadow_replay`` solves the SAME trace with a fresh
    always-cold arena at each reconcile boundary: the reconciliation
    bit-identity oracle ("a full solve on the accumulated columns"),
    which the stream engine's reconcile must match bit-for-bit.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import numpy as np

from protocol_tpu.obs.metrics import percentiles_ms
from protocol_tpu.stream.engine import StreamEngine
from protocol_tpu.stream.events import event_from_delta
from protocol_tpu.trace import format as tfmt

_ARENA_ENGINE = {"native-mt": "auction", "sinkhorn-mt": "sinkhorn"}


@contextlib.contextmanager
def _pin_recorded_isa(meta: dict):
    """Pin the native float pipeline to the one that PRODUCED the
    trace for the duration of a replay — the same contract as the batch
    replay (trace/replay.py): bit-for-bit outcome verification is only
    meaningful under the same per-ISA pipeline, and pre-ISA traces were
    recorded by the historical scalar pipeline. A host that cannot run
    the recorded ISA clamps down and verification reports honest
    divergence. Yields the EFFECTIVE isa (None when no native
    toolchain) and restores the caller's env var + effective ISA on
    exit — the pin is scoped to the replay, not the process."""
    import os as _os

    from protocol_tpu import native as _native

    pinned = str(meta.get("recorded_isa", "scalar"))
    prev_env = _os.environ.get("PROTOCOL_TPU_NATIVE_ISA")
    prev_eff: Optional[str] = None
    effective: Optional[str] = None
    try:
        prev_eff = _native.current_isa()
        effective = _native.set_isa(pinned)
    except _native.NativeBuildError:
        pass  # no toolchain: arena construction will fail honestly
    try:
        yield effective
    finally:
        if prev_env is None:
            _os.environ.pop("PROTOCOL_TPU_NATIVE_ISA", None)
        else:
            _os.environ["PROTOCOL_TPU_NATIVE_ISA"] = prev_env
        try:
            if prev_eff is not None:
                _native._apply_isa(_native.load(), prev_eff)
        except _native.NativeBuildError:
            pass


def _open_arena(snap: tfmt.Snapshot, engine: str, threads: int):
    """Prime a padded arena from a trace snapshot — identical padding
    and construction to the session/in-proc replay paths, so stream and
    batch replays share bit-identity by construction. Returns
    (arena, weights, padded p_cols, padded r_cols)."""
    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.services.session_store import _pad_cols

    if engine not in _ARENA_ENGINE:
        raise ValueError(
            f"stream replay engine must be one of "
            f"{tuple(_ARENA_ENGINE)}, got {engine!r}"
        )
    top_k = max(int(snap.top_k) or 64, 1)
    arena = NativeSolveArena(
        k=top_k, threads=threads, engine=_ARENA_ENGINE[engine]
    )
    pp = _pad_cols(snap.p_cols, snap.n_providers)
    rp = _pad_cols(snap.r_cols, snap.n_tasks)
    w = CostWeights(*snap.weights)
    arena.solve(tfmt._as_ns(pp), tfmt._as_ns(rp), w)
    return arena, w, pp, rp


def _events_of(trace: tfmt.Trace) -> list:
    evs = []
    for d in trace.deltas:
        ev = event_from_delta(d)
        if ev is None:
            raise ValueError(
                f"{trace.path}: delta tick {d.tick} carries no stream "
                "event meta — not a stream trace (synth one with "
                "`python -m protocol_tpu.stream synth`)"
            )
        evs.append(ev)
    return evs


def stream_replay(
    trace_path: str,
    engine: Optional[str] = None,
    threads: Optional[int] = None,
    reconcile_every: Optional[int] = None,
    gap_ceiling: Optional[float] = None,
    verify: bool = True,
    record_path: Optional[str] = None,
    chaos=None,
    final_reconcile: bool = True,
    keep_recon_p4ts: bool = False,
    extra_events: Optional[list] = None,
) -> dict:
    """Replay a stream trace event by event. Returns the report dict;
    ``report["divergence"]`` is None when every verified event
    reproduced the recorded plan bit-for-bit.

    ``chaos`` is a ``faults.plan.ChaosConfig`` (or None): events are
    delivered in the chaos'd order with duplicates injected; recorded-
    outcome verification is skipped (intermediate plans legitimately
    differ) and the caller compares final reconciled plans instead.

    ``extra_events`` are :class:`StreamEvent`s applied IN ORDER after
    the trace's events (never chaos'd) — the distributed firehose
    driver's storm/pad injections, so its fault-free baseline replays
    the exact event multiset a drilled fleet session absorbed."""
    trace = tfmt.read_trace(trace_path)
    with _pin_recorded_isa(trace.meta) as effective_isa:
        return _stream_replay(
            trace, trace_path, engine, threads, reconcile_every,
            gap_ceiling, verify, record_path, chaos, final_reconcile,
            keep_recon_p4ts, effective_isa, extra_events,
        )


def _stream_replay(
    trace: tfmt.Trace,
    trace_path: str,
    engine: Optional[str],
    threads: Optional[int],
    reconcile_every: Optional[int],
    gap_ceiling: Optional[float],
    verify: bool,
    record_path: Optional[str],
    chaos,
    final_reconcile: bool,
    keep_recon_p4ts: bool,
    effective_isa: Optional[str],
    extra_events: Optional[list] = None,
) -> dict:
    from protocol_tpu.trace.replay import parse_engine

    snap = trace.snapshot
    if snap is None:
        raise ValueError(f"{trace_path}: no snapshot frame")
    if engine:
        eng, eng_threads = parse_engine(engine)
    else:
        eng, eng_threads = parse_engine(snap.kernel or "native-mt")
    n_threads = eng_threads if threads is None else int(threads)
    n_recon = int(
        reconcile_every
        if reconcile_every is not None
        else trace.meta.get("reconcile_every", 64)
    )

    arena, weights, _pp, _rp = _open_arena(snap, eng, n_threads)
    se = StreamEngine(
        arena, weights,
        reconcile_every=n_recon,
        gap_ceiling=gap_ceiling,
    )
    n_t = snap.n_tasks

    events = _events_of(trace)
    order = list(range(len(events)))
    if chaos is not None and chaos.active():
        from protocol_tpu.faults.plan import (
            FaultSchedule,
            event_delivery_order,
        )

        order = event_delivery_order(FaultSchedule(chaos), len(events))
    if extra_events:
        # injected (storm/pad) events are appended AFTER the trace's
        # delivery order, always in-order: their sentinel seq tiers
        # make the converged columns order-independent anyway (see
        # dstream.fanout), but recorded-outcome verification only
        # covers the trace prefix either way
        base = len(events)
        events = events + list(extra_events)
        order = order + list(range(base, len(events)))

    writer = None
    if record_path is not None:
        meta = dict(trace.meta)
        meta.pop("version", None)
        meta.update(
            stream=True,
            reconcile_every=n_recon,
            recorded_engine=eng,
            recorded_threads=n_threads,
            source_trace=trace_path,
        )
        if effective_isa is not None:
            # provenance for the NEXT replay's pin (and the CI
            # replay-identity job's audit of committed goldens)
            meta["recorded_isa"] = effective_isa
        writer = tfmt.TraceWriter(record_path, meta=meta)
        writer.write_snapshot(
            snap.trace_id, snap.fingerprint, snap.request_v2()
        )
        writer.write_outcome(
            0, np.asarray(arena._p4t, np.int32)[:n_t],
            metrics={
                k: v for k, v in arena.last_stats.items()
                if isinstance(v, (int, float, bool, str))
            },
        )

    report: dict = {
        "trace": trace_path,
        "engine": eng,
        "threads": n_threads,
        "reconcile_every": n_recon,
        "providers": snap.n_providers,
        "tasks": n_t,
        "events": 0,
        "extra_events": len(extra_events or ()),
        "verified_events": 0,
        "divergence": None,
        "deduped": 0,
        "reconciles": 0,
        "gap_max": 0.0,
        "divergence_rows_max": 0,
        "cand_cold_passes": 0,
        "event_wall_ms": [],
        "reconcile_wall_ms": [],
        "recon_ticks": [],
    }
    recon_p4ts: list = []
    gap_every_event: list = []
    delivered = 0
    try:
        for idx in order:
            ev = events[idx]
            t0 = time.perf_counter()
            res = se.apply(ev)
            wall_ms = (time.perf_counter() - t0) * 1e3
            delivered += 1
            report["events"] += 1
            report["cand_cold_passes"] += int(
                res.stats.get("cand_cold_passes", 0)
            )
            if res.reconciled:
                report["reconcile_wall_ms"].append(round(wall_ms, 3))
                report["recon_ticks"].append(delivered)
                if keep_recon_p4ts:
                    recon_p4ts.append(res.plan[:n_t].copy())
            elif not res.deduped:
                report["event_wall_ms"].append(round(wall_ms, 3))
            gap_every_event.append(res.gap_per_task)
            if writer is not None:
                writer.write_delta_cols(
                    delivered, ev.provider_rows, ev.p_cols or None,
                    ev.task_rows, ev.r_cols or None, events=[ev.meta()],
                )
                writer.write_outcome(
                    delivered, res.plan[:n_t],
                    metrics={
                        "apply_ms": round(res.apply_ms, 3),
                        "gap_per_task": res.gap_per_task,
                        "divergence_rows": res.divergence_rows,
                        "reconciled": res.reconciled,
                        "deduped": res.deduped,
                        "repair_rows": res.repair_rows,
                        "kind": ev.kind,
                    },
                )
            if verify and chaos is None:
                rec = trace.outcome_for(delivered)
                if rec is not None:
                    report["verified_events"] += 1
                    got = res.plan[:n_t]
                    if not np.array_equal(got, rec.provider_for_task):
                        rows = np.flatnonzero(
                            got != rec.provider_for_task
                        )
                        report["divergence"] = {
                            "event": delivered,
                            "kind": ev.kind,
                            "n_rows": int(rows.size),
                            "rows": rows[:64].tolist(),
                        }
                        break
        if final_reconcile and se.events_since_reconcile > 0 and (
            report["divergence"] is None
        ):
            res = se.reconcile()
            report["reconciles_final"] = True
            report["recon_ticks"].append(delivered)
            report["reconcile_wall_ms"].append(round(res.apply_ms, 3))
            if keep_recon_p4ts:
                recon_p4ts.append(res.plan[:n_t].copy())
    finally:
        if writer is not None:
            writer.close()

    snap_eng = se.snapshot()
    report["deduped"] = snap_eng["events_deduped"]
    report["reconciles"] = snap_eng["reconciles"]
    report["events_stale"] = snap_eng["events_stale"]
    report["gap_max"] = snap_eng["gap_max"]
    report["gap_served_max"] = snap_eng["gap_served_max"]
    report["divergence_rows_max"] = snap_eng["divergence_max"]
    report["gap_per_event"] = [round(g, 6) for g in gap_every_event]
    report["assigned_last"] = int((arena._p4t[:n_t] >= 0).sum())
    if report["event_wall_ms"]:
        report["event_percentiles"] = percentiles_ms(
            report["event_wall_ms"]
        )
    if keep_recon_p4ts:
        report["recon_p4ts"] = recon_p4ts
    return report


def batch_shadow_replay(
    trace_path: str,
    boundaries: list,
    engine: Optional[str] = None,
    threads: Optional[int] = None,
) -> dict:
    """The reconciliation oracle: apply the trace's events cumulatively
    to the snapshot columns and run a FULL COLD batch solve at each
    boundary (event counts, 1-based) with a fresh always-cold arena —
    "the equivalent batch replay" the stream engine's reconcile must be
    bit-identical to. Returns {"p4ts": [plan per boundary], ...}."""
    trace = tfmt.read_trace(trace_path)
    # the oracle must solve under the SAME recorded pipeline as the
    # stream replay it is compared against, or the bit-identity gate
    # would report cross-ISA float noise as a reconcile bug
    with _pin_recorded_isa(trace.meta):
        return _batch_shadow_replay(trace, trace_path, boundaries,
                                    engine, threads)


def _batch_shadow_replay(
    trace: tfmt.Trace,
    trace_path: str,
    boundaries: list,
    engine: Optional[str],
    threads: Optional[int],
) -> dict:
    from protocol_tpu.trace.replay import parse_engine

    snap = trace.snapshot
    if snap is None:
        raise ValueError(f"{trace_path}: no snapshot frame")
    if engine:
        eng, eng_threads = parse_engine(engine)
    else:
        eng, eng_threads = parse_engine(snap.kernel or "native-mt")
    n_threads = eng_threads if threads is None else int(threads)

    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.services.session_store import _pad_cols

    top_k = max(int(snap.top_k) or 64, 1)
    # cold_every=0: every solve re-grounds — the batch-shadow arena is
    # the "full batch solve on the accumulated columns" oracle, with no
    # warm path dependence on intermediate windows
    arena = NativeSolveArena(
        k=top_k, threads=n_threads, engine=_ARENA_ENGINE[eng],
        cold_every=0,
    )
    w = CostWeights(*snap.weights)
    p_cols = {n: a.copy() for n, a in snap.p_cols.items()}
    r_cols = {n: a.copy() for n, a in snap.r_cols.items()}
    events = _events_of(trace)
    n_t = snap.n_tasks
    p4ts: list = []
    walls: list = []
    want = sorted(int(b) for b in boundaries)
    for i, ev in enumerate(events, start=1):
        for rows, vals, cols in (
            (ev.provider_rows, ev.p_cols, p_cols),
            (ev.task_rows, ev.r_cols, r_cols),
        ):
            if rows is None or not np.asarray(rows).size:
                continue
            for name, v in vals.items():
                cols[name][np.asarray(rows)] = v
        if want and i == want[0]:
            want.pop(0)
            t0 = time.perf_counter()
            pp = _pad_cols(p_cols, snap.n_providers)
            rp = _pad_cols(r_cols, snap.n_tasks)
            p4t = arena.solve(tfmt._as_ns(pp), tfmt._as_ns(rp), w)
            walls.append(round((time.perf_counter() - t0) * 1e3, 3))
            p4ts.append(np.asarray(p4t, np.int32)[:n_t].copy())
    return {
        "trace": trace_path,
        "engine": eng,
        "threads": n_threads,
        "boundaries": sorted(int(b) for b in boundaries),
        "p4ts": p4ts,
        "solve_wall_ms": walls,
    }
