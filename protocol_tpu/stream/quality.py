"""Incremental certified optimality gap for the streamed plan — the
"certified bounded divergence" half of the stream engine.

The quality plane's :func:`protocol_tpu.obs.quality.duality_gap` is an
O(T*K) scan; per event that alone would burn the sub-tick budget. This
tracker maintains the SAME certificate incrementally: rebase exactly at
every reconcile, then per event recompute only the rows the event
touched and keep every other row's stale contribution — which is still
a sound UPPER bound, by two monotonicity arguments:

  * **Untouched rows' slack can only shrink.** Between reconciles the
    auction's prices are monotone non-decreasing, and a price move on a
    provider comes with a seat move on it (single-seat providers), so
    an untouched row has the same seat at the same price — its
    ``seat_adj`` is exact — while its ``best = min_k(c_k + price_k)``
    can only have RISEN since the stale value was computed. Stale
    ``slack = seat_adj - best_stale >= slack_true``.
  * **The idle-price addend is a superset.** The exact certificate sums
    prices over *reachable* idle providers; the tracker sums over ALL
    idle positive-price providers (an O(P) vector op — maintaining the
    reachable set incrementally would need pre-repair row snapshots).
    A superset of nonnegative terms only loosens the bound, and any
    nonnegative dual point certifies.

So ``tracker gap >= duality_gap >= plan_cost - OPT`` at every event:
the ceiling the CI gate holds on the tracker is a certified bound on
how far the streamed plan's cost can sit above the optimum — and since
the batch shadow plan's cost is itself >= OPT, it also bounds
``cost(streamed) - cost(batch)``: the certified divergence bound.

The price cap (``2*cmax + 10``, the engine's give-up magnitude) is
frozen at rebase: capping with ANY fixed value yields a valid dual
point, and a frozen cap preserves the monotone-capped-price argument
above. Sinkhorn streams re-derive referee prices per solve (not
monotone), so the stream engine runs the exact scan there instead.

Determinism contract: pure functions of (candidates, plan, duals) — no
clocks, no randomness.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from protocol_tpu.obs.quality import _INFEASIBLE


class GapTracker:
    """Incrementally-maintained certified duality-gap upper bound."""

    def __init__(self):
        self._cap = 0.0
        self._best: Optional[np.ndarray] = None  # f64 [T]
        self._seat_adj: Optional[np.ndarray] = None  # f64 [T]
        self._seat_c: Optional[np.ndarray] = None  # f64 [T], 0 unassigned
        self._slack: Optional[np.ndarray] = None  # f64 [T]
        self._p4t: Optional[np.ndarray] = None  # i32 [T] copy
        self._price: Optional[np.ndarray] = None  # f64 [P] capped copy

    @property
    def primed(self) -> bool:
        return self._slack is not None

    def _row_terms(
        self,
        cand_p: np.ndarray,
        cand_c: np.ndarray,
        p4t: np.ndarray,
        price_c: np.ndarray,
        rows: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(best, seat_adj, seat_c) for the given rows at the given
        capped prices — the exact per-row certificate terms. A row
        whose seat is missing from its candidate list contributes zero
        (same exclusion rule as ``duality_gap``; the arena's seat guard
        makes that unreachable in practice)."""
        cp = cand_p[rows]
        cc = cand_c[rows].astype(np.float64)
        feas = (cp >= 0) & (cc < _INFEASIBLE * 0.5)
        adj = np.where(feas, cc + price_c[np.maximum(cp, 0)], np.inf)
        best = adj.min(axis=1)
        seat = p4t[rows]
        seat_adj = np.zeros(rows.size, np.float64)
        seat_c = np.zeros(rows.size, np.float64)
        assigned = seat >= 0
        if assigned.any():
            m = (cp == seat[:, None]) & feas
            has = m.any(axis=1) & assigned
            j = m.argmax(axis=1)
            arows = np.flatnonzero(has)
            seat_c[arows] = cc[arows, j[arows]]
            seat_adj[arows] = seat_c[arows] + price_c[seat[arows]]
        return best, seat_adj, seat_c

    def rebase(
        self,
        cand_p: np.ndarray,
        cand_c: np.ndarray,
        p4t: np.ndarray,
        price: np.ndarray,
    ) -> dict:
        """Exact full recompute (reconcile / prime time): freezes the
        price cap and rebuilds every per-row term."""
        cand_p = np.asarray(cand_p)
        cand_c = np.asarray(cand_c)
        p4t = np.asarray(p4t, np.int32)
        T = p4t.shape[0]
        feas = (cand_p >= 0) & (cand_c < _INFEASIBLE * 0.5)
        cmax = float(cand_c[feas].max()) if feas.any() else 0.0
        self._cap = 2.0 * cmax + 10.0
        self._price = np.minimum(
            np.asarray(price, np.float64), self._cap
        )
        all_rows = np.arange(T)
        self._best, self._seat_adj, self._seat_c = self._row_terms(
            cand_p, cand_c, p4t, self._price, all_rows
        )
        self._slack = np.maximum(self._seat_adj - self._best, 0.0)
        # unassigned rows (or seat-missing rows) carry no slack: the
        # certificate covers exactly the assigned task set
        self._slack[self._seat_adj == 0.0] = 0.0
        self._p4t = p4t.copy()
        return self._report()

    def update(
        self,
        cand_p: np.ndarray,
        cand_c: np.ndarray,
        p4t: np.ndarray,
        price: np.ndarray,
        repair_mask: Optional[np.ndarray],
    ) -> dict:
        """One event's incremental refresh. ``repair_mask`` [T] flags
        rows whose candidate content moved (the arena's ``repair``
        output); seat/price-moved rows are derived here from the plan
        and price deltas."""
        if not self.primed:
            return self.rebase(cand_p, cand_c, p4t, price)
        p4t = np.asarray(p4t, np.int32)
        price_c = np.minimum(np.asarray(price, np.float64), self._cap)
        touched = (
            np.asarray(repair_mask, bool).copy()
            if repair_mask is not None
            else np.zeros(p4t.shape[0], bool)
        )
        touched |= p4t != self._p4t
        # rows whose SEAT's price moved: derived from the price delta
        # (O(T) gather + compare) rather than argued from auction
        # internals — exactness here is what keeps untouched rows'
        # seat_adj exact
        seated = p4t >= 0
        if seated.any():
            moved = price_c != self._price
            touched |= seated & moved[np.maximum(p4t, 0)]
        rows = np.flatnonzero(touched)
        if rows.size:
            best, seat_adj, seat_c = self._row_terms(
                cand_p, cand_c, p4t, price_c, rows
            )
            self._best[rows] = best
            self._seat_adj[rows] = seat_adj
            self._seat_c[rows] = seat_c
            slack = np.maximum(seat_adj - best, 0.0)
            slack[seat_adj == 0.0] = 0.0
            self._slack[rows] = slack
        self._p4t = p4t.copy()
        self._price = price_c
        return self._report()

    def _report(self) -> dict:
        p4t = self._p4t
        used = np.zeros(self._price.shape[0], bool)
        seated = p4t[p4t >= 0]
        used[seated] = True
        idle_price = float(self._price[~used & (self._price > 0)].sum())
        cs_slack = float(self._slack.sum())
        plan_cost = float(self._seat_c.sum())
        gap_total = cs_slack + idle_price
        n = int((p4t >= 0).sum())
        return {
            "plan_cost": round(plan_cost, 4),
            "dual_bound": round(plan_cost - gap_total, 4),
            "gap_total": round(gap_total, 6),
            "gap_per_task": round(gap_total / max(n, 1), 6),
            "cs_slack": round(cs_slack, 6),
            "idle_price": round(idle_price, 6),
            "incremental": True,
        }
