"""Event taxonomy + idempotence machinery for the streaming engine.

The reference orchestrator mutates pool allocation the moment a
heartbeat, invite, or ejection arrives; our event vocabulary mirrors
that control plane:

  ``heartbeat``   price/load drift on a live provider row (the
                  per-heartbeat common case)
  ``join``        a provider row flips valid=True (fresh features)
  ``leave``       a provider row flips valid=False (disconnect/ejection)
  ``task``        a task row's requirement churns (submit/update)
  ``mass``        a multi-row burst (regional outage / reconnect wave) —
                  outside the per-source supersession contract, see below

An event names its churned rows EXPLICITLY and carries the FULL current
row state for them (the wire-delta shape, never an increment). That
full-state contract is what makes chaos cheap to survive:

  * every event carries a ``(source, seq)`` pair with ``seq`` strictly
    monotonic per source (one source = one provider node or one task
    submitter, always churning the same row set);
  * a DUPLICATED event re-arrives with a seq the engine already
    committed -> dropped (counted, never double-applied);
  * a REORDERED event arrives with a seq below the source's high-water
    mark -> it was superseded by the newer full-state event that
    overtook it -> dropped, and the columns still converge to exactly
    the in-order outcome ("latest-wins" is exact for full-state rows).

``mass`` events may overlap other sources' rows, so supersession does
not hold across sources for them — the synth factory only emits them
into latency workloads, never chaos'd idempotence drills.

Determinism contract: no clocks, no randomness (the determinism lint
covers this package); arrival timestamps are workload DATA (``at_us``
from the seeded synth factory), never read from a wall clock.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

EVENT_KINDS = ("heartbeat", "join", "leave", "task", "mass")


@dataclasses.dataclass
class StreamEvent:
    """One churn event: explicit rows + full-state values for them.

    ``p_cols``/``r_cols`` are column dicts with one value per row index
    (trace/wire dtypes); either side may be empty. ``at_us`` is the
    scheduled arrival offset of the open-loop workload (data, not a
    clock read)."""

    kind: str
    source: str
    seq: int
    provider_rows: np.ndarray
    p_cols: dict
    task_rows: np.ndarray
    r_cols: dict
    at_us: int = 0

    @property
    def n_rows(self) -> int:
        return int(self.provider_rows.size + self.task_rows.size)

    def meta(self) -> dict:
        """The JSON side-channel a trace DELTA frame carries."""
        return {
            "kind": self.kind,
            "source": self.source,
            "seq": int(self.seq),
            "at_us": int(self.at_us),
            "rows": self.n_rows,
        }


def event_from_delta(delta) -> Optional[StreamEvent]:
    """Rebuild a :class:`StreamEvent` from a trace ``DeltaRecord`` whose
    events list carries a stream-event meta dict (the synth factory's
    one-event-per-frame layout). None when the frame carries no stream
    meta (a plain batch-trace delta)."""
    meta = next(
        (e for e in (delta.events or []) if "source" in e and "seq" in e),
        None,
    )
    if meta is None:
        return None
    return StreamEvent(
        kind=str(meta.get("kind", "heartbeat")),
        source=str(meta["source"]),
        seq=int(meta["seq"]),
        provider_rows=delta.provider_rows,
        p_cols=delta.p_cols,
        task_rows=delta.task_rows,
        r_cols=delta.r_cols,
        at_us=int(meta.get("at_us", 0)),
    )


class SourceDedup:
    """Per-source monotonic high-water marks: the never-double-apply
    half of the idempotence contract. ``admit`` commits; ``stale`` only
    peeks (the wire path decides before touching any state).

    The map is LRU-bounded: sources are churn-emitter ids (one per
    provider/task row at worst), and an unbounded dict would grow one
    entry per id ever seen — the same client-minted-key argument as
    ObsRegistry's session cap."""

    def __init__(self, max_sources: int = 1 << 20):
        from collections import OrderedDict

        self.max_sources = int(max_sources)
        self._seq: "OrderedDict[str, int]" = OrderedDict()
        self.deduped = 0

    def stale(self, source: str, seq: int) -> bool:
        last = self._seq.get(source)
        return last is not None and int(seq) <= last

    def admit(self, source: str, seq: int) -> bool:
        """True = fresh (committed as the new high-water mark); False =
        duplicate/superseded (counted, caller must not apply)."""
        if self.stale(source, seq):
            self.deduped += 1
            return False
        self._seq[source] = int(seq)
        self._seq.move_to_end(source)
        while len(self._seq) > self.max_sources:
            self._seq.popitem(last=False)
        return True

    # ---------------- checkpoint travel (ISSUE 20) ----------------

    def export_cursors(self, limit: Optional[int] = None) -> dict:
        """Serialize the high-water marks (LRU order, newest last) for
        journal travel. ``limit`` caps the export at the NEWEST entries
        — the same argument as the LRU bound itself: a cursor old
        enough to fall off the cap protects against retransmits no
        client ladder still sends. ``truncated`` counts what was
        dropped so the cap is visible, never silent."""
        items = list(self._seq.items())
        truncated = 0
        if limit is not None and len(items) > int(limit):
            truncated = len(items) - int(limit)
            items = items[-int(limit):]
        return {
            "sources": [s for s, _ in items],
            "seqs": [int(q) for _, q in items],
            "deduped": int(self.deduped),
            "truncated": truncated,
        }

    def restore_cursors(self, state: dict) -> None:
        """Re-seed the marks from an exported dict (migration re-arm).
        Existing entries merge by max — restoring over a live map can
        only tighten, never regress, a high-water mark."""
        for s, q in zip(
            state.get("sources") or (), state.get("seqs") or ()
        ):
            s, q = str(s), int(q)
            prev = self._seq.get(s)
            self._seq[s] = q if prev is None else max(prev, q)
            self._seq.move_to_end(s)
        while len(self._seq) > self.max_sources:
            self._seq.popitem(last=False)
        self.deduped = int(state.get("deduped", self.deduped))


def coalesce(events: list) -> Optional[StreamEvent]:
    """Merge a burst of pending events into ONE synthetic event — the
    coalescing window's flush. Later events override earlier ones on
    overlapping rows (list order IS arrival order; the caller already
    dedup-filtered, so arrival order respects per-source seq order and
    latest-wins is exact). Returns None for an empty burst; a single
    event passes through untouched."""
    if not events:
        return None
    if len(events) == 1:
        return events[0]

    def _merge(rows_name, cols_name):
        # last-writer-wins per row: walk in arrival order, keep the
        # final value each row saw
        vals: dict[int, dict] = {}
        for ev in events:
            rows = getattr(ev, rows_name)
            cols = getattr(ev, cols_name)
            for i, r in enumerate(np.asarray(rows).tolist()):
                vals[int(r)] = {n: a[i] for n, a in cols.items()}
        if not vals:
            return np.zeros(0, np.int32), {}
        idx = sorted(vals)
        names = list(vals[idx[0]])
        out_rows = np.asarray(idx, np.int32)
        out_cols = {
            n: np.stack([np.asarray(vals[r][n]) for r in idx])
            for n in names
        }
        return out_rows, out_cols

    prow, p_cols = _merge("provider_rows", "p_cols")
    trow, r_cols = _merge("task_rows", "r_cols")
    last = events[-1]
    return StreamEvent(
        kind="coalesced",
        source=last.source,
        seq=last.seq,
        provider_rows=prow,
        p_cols=p_cols,
        task_rows=trow,
        r_cols=r_cols,
        at_us=last.at_us,
    )
