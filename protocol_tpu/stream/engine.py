"""The online assignment engine: per-event localized repair over a warm
arena, with certified bounded divergence and periodic reconciliation.

One :class:`StreamEngine` binds to a PRIMED :class:`NativeSolveArena`
(a batch ``solve`` ran at least once, so the persistent candidate
structure and duals exist) and turns churn events into sub-tick plan
updates:

  apply(event)   dedup by (source, seq) -> arena.apply_rows (dirty-row
                 candidate repair + one masked fine-eps warm engine
                 pass, O(churned rows)) -> incremental certified-gap
                 refresh -> divergence count vs the last reconciled
                 plan. Zero full-matrix candidate passes, ever.
  reconcile()    arena.reconcile(): a full batch solve over the
                 (repaired-exact) structure from scratch duals —
                 bit-identical to a cold batch solve on the current
                 columns — then an exact gap rebase and a divergence/
                 staleness counter reset.

Reconciliation runs automatically every ``reconcile_every`` events or
when the certified gap breaches ``gap_ceiling`` (the quality trigger).
The bounded-staleness watchdog mirrors the PR 9 contract: if reconcile
is starved past ``max_stale_events`` (auto-reconcile off, or the due
flag ignored by the driver), every further streamed answer is flagged
AND counted stale — staleness is a contract, never silent drift.

Concurrency: ``apply``/``reconcile`` serialize on one "stream"-domain
lock (rank between the session lock and every leaf it uses), so the
wire servicer (already under the session lock) and a standalone
multi-threaded driver both get the same linearized event order.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from protocol_tpu.obs.quality import duality_gap
from protocol_tpu.obs.spans import TRACER as _tracer
from protocol_tpu.stream.events import SourceDedup, StreamEvent, coalesce
from protocol_tpu.stream.quality import GapTracker
from protocol_tpu.utils.lockwitness import make_lock


@dataclasses.dataclass
class StreamResult:
    """One apply's answer: the live streamed plan + its certificates."""

    plan: np.ndarray  # provider_for_task [T] i32 (arena row space)
    deduped: bool = False
    reconciled: bool = False
    stale: bool = False
    events_since_reconcile: int = 0
    divergence_rows: int = 0
    gap_per_task: float = 0.0
    apply_ms: float = 0.0
    repair_rows: int = 0
    stats: dict = dataclasses.field(default_factory=dict)


class StreamEngine:
    def __init__(
        self,
        arena,
        weights,
        reconcile_every: int = 256,
        gap_ceiling: Optional[float] = None,
        max_stale_events: int = 4096,
        auto_reconcile: bool = True,
        event_eps_start: Optional[float] = None,
    ):
        if arena._p4t is None:
            raise RuntimeError(
                "StreamEngine needs a primed arena (run a batch solve "
                "first — the cold solve IS event tick 0)"
            )
        self.arena = arena
        self.weights = weights
        self.reconcile_every = int(reconcile_every)
        self.gap_ceiling = gap_ceiling
        self.max_stale_events = int(max_stale_events)
        self.auto_reconcile = auto_reconcile
        self.event_eps_start = event_eps_start
        self._lock = make_lock("stream")
        self.dedup = SourceDedup()
        self._gap = GapTracker()
        # divergence is measured against the last reconciled plan: the
        # streamed path's drift since the last full solve
        self._recon_p4t = np.asarray(arena._p4t, np.int32).copy()
        self.events_since_reconcile = 0
        self.reconcile_due = False
        self.due_reason = ""
        # counters (obs plane reads these; never fed back into solves)
        self.events_applied = 0
        self.events_stale = 0
        self.reconciles = 0
        self.divergence_max = 0
        # observed peak (pre-reconcile breaches included) vs the peak
        # the engine actually ANSWERED with — a ceiling breach reconciles
        # inline and serves the fresh plan, so the served-gap contract
        # is the gate's floor while the observed peak is the alert
        self.gap_max = 0.0
        self.gap_served_max = 0.0
        self._last_recon_gap = self._rebase_gap()
        # the live plan's most recent certificate — what a deduped ack
        # honestly reports for the plan it serves
        self.gap_last = float(
            self._last_recon_gap.get("gap_per_task", 0.0)
        )

    # ---------------- internals ----------------

    def _rebase_gap(self) -> dict:
        a = self.arena
        return self._gap.rebase(a._cand_p, a._cand_c, a._p4t, a._price)

    def _gap_after_event(self, repair_mask) -> dict:
        a = self.arena
        if a.engine == "sinkhorn":
            # referee prices are re-derived per solve (not monotone), so
            # the incremental upper-bound argument does not hold: run
            # the exact O(T*K) scan — proportionate next to the per-
            # event O(nnz) potential iterations this engine already pays
            return duality_gap(a._cand_p, a._cand_c, a._p4t, a._price)
        return self._gap.update(
            a._cand_p, a._cand_c, a._p4t, a._price, repair_mask
        )

    def stale_event(self, source: str, seq: int) -> bool:
        """Peek-only dedup check (the wire path decides whether to apply
        the session columns BEFORE committing anything)."""
        with self._lock:
            return self.dedup.stale(source, seq)

    # ---------------- the hot path ----------------

    def apply(self, event: StreamEvent) -> StreamResult:
        """Apply one event to the live plan. O(churned rows); never a
        full-matrix candidate pass. A duplicate/superseded (source, seq)
        is dropped — counted, current plan answered, state untouched."""
        with self._lock:
            return self._apply_locked(event)

    def apply_burst(self, events: list) -> StreamResult:
        """The coalescing window's flush: dedup-filter the burst, merge
        survivors into ONE synthetic event (latest-wins per row — exact
        for full-state events), and apply it as a single repair pass.
        Arrival order inside the burst is preserved by the merge."""
        with self._lock:
            fresh = [
                ev for ev in events
                if self.dedup.admit(ev.source, ev.seq)
            ]
            merged = coalesce(fresh)
            if merged is None:
                return self._result(
                    self.arena._p4t.copy(), deduped=True, apply_ms=0.0
                )
            return self._apply_locked(merged, deduped_checked=True)

    def _apply_locked(
        self, event: StreamEvent, deduped_checked: bool = False
    ) -> StreamResult:
        t0 = time.perf_counter()
        if not deduped_checked and not self.dedup.admit(
            event.source, event.seq
        ):
            return self._result(
                self.arena._p4t.copy(),
                deduped=True,
                apply_ms=(time.perf_counter() - t0) * 1e3,
            )
        plan = self.arena.apply_rows(
            event.provider_rows, event.p_cols or None,
            event.task_rows, event.r_cols or None,
            self.weights,
            event_eps_start=self.event_eps_start,
        )
        stats = self.arena.last_stats
        # the repair mask (rows whose candidate content moved) is a gap
        # soundness input: a repaired-cheaper candidate lowers a row's
        # `best`, which RAISES its slack — those rows must recompute
        gap = self._gap_after_event(self.arena.last_repair_mask)
        self.events_applied += 1
        self.events_since_reconcile += 1
        gpt = float(gap.get("gap_per_task", 0.0))
        self.gap_max = max(self.gap_max, gpt)
        divergence = int((plan != self._recon_p4t).sum())
        self.divergence_max = max(self.divergence_max, divergence)
        if self.events_since_reconcile >= self.reconcile_every:
            self.reconcile_due, self.due_reason = True, "cadence"
        if self.gap_ceiling is not None and gpt > self.gap_ceiling:
            self.reconcile_due, self.due_reason = True, "gap"
        stale = False
        reconciled = False
        if self.reconcile_due and self.auto_reconcile:
            plan = self._reconcile_locked()
            reconciled = True
            divergence = 0
            gpt = float(self._last_recon_gap.get("gap_per_task", 0.0))
        if not reconciled and (
            self.events_since_reconcile > self.max_stale_events
        ):
            # the watchdog: reconcile starved past the bound — the
            # answer is still served (the delta was applied; columns
            # stay consistent) but flagged and counted, the PR 9
            # bounded-staleness shape
            stale = True
            self.events_stale += 1
        self.gap_served_max = max(self.gap_served_max, gpt)
        self.gap_last = gpt
        apply_ms = (time.perf_counter() - t0) * 1e3
        _tracer.point(
            "stream.event", kind=event.kind, rows=event.n_rows,
            reconciled=reconciled,
        )
        # COPY at the boundary: apply_rows/reconcile return the live
        # arena array, which the NEXT event mutates in place (dirty
        # re-seats write -1 rows) — a caller retaining the plan (the
        # servicer's retransmit cache above all) must never see it
        # change under them
        return self._result(
            plan.copy(), reconciled=reconciled, stale=stale,
            divergence_rows=divergence, gap_per_task=gpt,
            apply_ms=apply_ms,
            repair_rows=int(stats.get("repair_rows", 0)),
            stats=stats,
        )

    def _result(self, plan, **kw) -> StreamResult:
        return StreamResult(
            plan=plan,
            events_since_reconcile=self.events_since_reconcile,
            **kw,
        )

    # ---------------- reconciliation ----------------

    def reconcile(self) -> StreamResult:
        """Run the full batch solve now (drivers with auto_reconcile off
        call this on their own cadence)."""
        with self._lock:
            t0 = time.perf_counter()
            plan = self._reconcile_locked()
            return self._result(
                plan.copy(), reconciled=True,
                gap_per_task=float(
                    self._last_recon_gap.get("gap_per_task", 0.0)
                ),
                apply_ms=(time.perf_counter() - t0) * 1e3,
                stats=self.arena.last_stats,
            )

    def _reconcile_locked(self) -> np.ndarray:
        plan = self.arena.reconcile()
        self._recon_p4t = np.asarray(plan, np.int32).copy()
        self._last_recon_gap = self._rebase_gap()
        self.gap_max = max(
            self.gap_max,
            float(self._last_recon_gap.get("gap_per_task", 0.0)),
        )
        self.reconciles += 1
        self.events_since_reconcile = 0
        self.reconcile_due = False
        self.due_reason = ""
        self.gap_last = float(
            self._last_recon_gap.get("gap_per_task", 0.0)
        )
        return plan

    # ---------------- checkpoint travel (ISSUE 20) ----------------

    def export_state(self, max_cursor_sources: int = 1 << 16) -> dict:
        """The full re-armable stream state for journal travel: config,
        the per-source dedup cursors, the reconcile-cadence cursor, and
        the obs counters. JSON-serializable by construction (the
        checkpoint META frame carries it).

        What does NOT travel: the gap tracker and the divergence
        baseline. Both are derived EXACTLY from the restored arena at
        re-arm time (``GapTracker.rebase`` over the restored duals is
        the same exact certificate; the restored plan becomes the new
        divergence reference), so serializing them would only add a
        second source of truth that could disagree with the arrays."""
        with self._lock:
            return {
                "reconcile_every": int(self.reconcile_every),
                "gap_ceiling": self.gap_ceiling,
                "max_stale_events": int(self.max_stale_events),
                "auto_reconcile": bool(self.auto_reconcile),
                "event_eps_start": self.event_eps_start,
                "events_since_reconcile": int(
                    self.events_since_reconcile
                ),
                "events_applied": int(self.events_applied),
                "events_stale": int(self.events_stale),
                "reconciles": int(self.reconciles),
                "divergence_max": int(self.divergence_max),
                "gap_max": float(self.gap_max),
                "gap_served_max": float(self.gap_served_max),
                "dedup": self.dedup.export_cursors(
                    limit=max_cursor_sources
                ),
            }

    @classmethod
    def from_state(cls, arena, weights, state: dict) -> "StreamEngine":
        """Re-arm over a restored PRIMED arena (migration / restart).
        The dedup cursors make a retransmitted (source, seq) that
        straddles the process boundary dedup at the target exactly as
        it would have at the origin — the wire tick/CRC cursor only
        covers the LAST tick, so without these a chaos'd retransmit
        arriving as a fresh tick after the handoff would double-apply.
        The cadence cursor keeps the migrated stream's reconcile
        boundaries aligned with its fault-free replay."""
        eng = cls(
            arena, weights,
            reconcile_every=int(state.get("reconcile_every", 256)),
            gap_ceiling=state.get("gap_ceiling"),
            max_stale_events=int(state.get("max_stale_events", 4096)),
            auto_reconcile=bool(state.get("auto_reconcile", True)),
            event_eps_start=state.get("event_eps_start"),
        )
        dd = state.get("dedup")
        if dd:
            eng.dedup.restore_cursors(dd)
        eng.events_since_reconcile = int(
            state.get("events_since_reconcile", 0)
        )
        eng.events_applied = int(state.get("events_applied", 0))
        eng.events_stale = int(state.get("events_stale", 0))
        eng.reconciles = int(state.get("reconciles", 0))
        eng.divergence_max = int(state.get("divergence_max", 0))
        eng.gap_max = max(eng.gap_max, float(state.get("gap_max", 0.0)))
        eng.gap_served_max = max(
            eng.gap_served_max, float(state.get("gap_served_max", 0.0))
        )
        # a flush can land between the cadence trigger and the (driver-
        # owned) reconcile when auto_reconcile is off — re-raise the due
        # flag instead of silently restarting the window
        if eng.events_since_reconcile >= eng.reconcile_every:
            eng.reconcile_due, eng.due_reason = True, "cadence"
        return eng

    # ---------------- observability ----------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "events_applied": self.events_applied,
                "events_deduped": self.dedup.deduped,
                "events_stale": self.events_stale,
                "events_since_reconcile": self.events_since_reconcile,
                "reconciles": self.reconciles,
                "reconcile_due": self.reconcile_due,
                "due_reason": self.due_reason,
                "divergence_max": self.divergence_max,
                "gap_max": round(self.gap_max, 6),
                "gap_served_max": round(self.gap_served_max, 6),
            }
