"""CLI: ``python -m protocol_tpu.stream {synth,replay}``.

  synth    write a parameterized synthetic EVENT trace (one DELTA frame
           per churn event, deterministic open-loop arrival schedule)
  replay   feed a stream trace through the online engine event by
           event; verifies recorded outcomes bit-for-bit (non-zero exit
           on divergence), optionally under seeded event chaos, and/or
           re-records outcomes (how the golden stream trace is made)
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m protocol_tpu.stream")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("synth", help="write a synthetic event trace")
    sp.add_argument("path")
    sp.add_argument("--providers", type=int, default=1024)
    sp.add_argument("--tasks", type=int, default=1024)
    sp.add_argument("--events", type=int, default=256)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--kernel", default="native-mt")
    sp.add_argument("--top-k", type=int, default=64)
    sp.add_argument("--rate-hz", type=float, default=1000.0)
    sp.add_argument("--reconcile-every", type=int, default=64)
    sp.add_argument("--headroom", type=float, default=0.1)

    rp = sub.add_parser("replay", help="replay a stream trace")
    rp.add_argument("path")
    rp.add_argument("--engine", default=None)
    rp.add_argument("--threads", type=int, default=None)
    rp.add_argument("--reconcile-every", type=int, default=None)
    rp.add_argument("--gap-ceiling", type=float, default=None)
    rp.add_argument("--record", default=None)
    rp.add_argument("--no-verify", action="store_true")
    rp.add_argument(
        "--chaos", default=None,
        help="seeded event-chaos spec, e.g. seed=3,drop=0.1,dup=0.1,"
             "reorder=0.1",
    )

    args = ap.parse_args(argv)
    if args.cmd == "synth":
        from protocol_tpu.trace.synth import synth_event_trace

        path = synth_event_trace(
            args.path,
            n_providers=args.providers,
            n_tasks=args.tasks,
            events=args.events,
            seed=args.seed,
            kernel=args.kernel,
            top_k=args.top_k,
            rate_hz=args.rate_hz,
            reconcile_every=args.reconcile_every,
            headroom=args.headroom,
        )
        print(json.dumps({"path": path, "events": args.events}))
        return 0

    from protocol_tpu.stream.replay import stream_replay

    chaos = None
    if args.chaos:
        from protocol_tpu.faults.plan import ChaosConfig

        chaos = ChaosConfig.from_spec(args.chaos)
    report = stream_replay(
        args.path,
        engine=args.engine,
        threads=args.threads,
        reconcile_every=args.reconcile_every,
        gap_ceiling=args.gap_ceiling,
        verify=not args.no_verify,
        record_path=args.record,
        chaos=chaos,
    )
    slim = {
        k: v for k, v in report.items()
        if k not in ("event_wall_ms", "gap_per_event", "recon_p4ts")
    }
    print(json.dumps(slim, indent=2, default=str))
    return 1 if report.get("divergence") else 0


if __name__ == "__main__":
    sys.exit(main())
