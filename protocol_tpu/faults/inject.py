"""Fault injectors: where the schedule's decisions land on the wire.

Two injection sites, both driven by the same :class:`FaultSchedule` so
one seed describes the whole run:

  * :class:`ChaosClient` — wraps a ``SchedulerBackendClient`` and
    applies client-observable transport faults: dropped requests AND
    dropped responses (the server processed, the answer died — the case
    that exercises idempotent retransmit), injected delay, corrupted
    TensorBlob bytes (the input-hardening refusal path), truncated
    OpenSession chunk streams, and duplicated deltas (the dedup path).
  * :class:`ChaosServerInterceptor` — a real ``grpc.ServerInterceptor``
    that drops (UNAVAILABLE before the servicer runs) or delays RPCs
    server-side, so the client's retry ladder sees genuine mid-stream
    failures on a live HTTP/2 connection.

Corruption mutates a COPY of the request: the caller's message is never
damaged, exactly like a wire-level bit flip leaves the sender's buffer
intact. A corrupted frame must be REJECTED by the server's decode
hardening (INVALID_ARGUMENT) before it can poison a session arena —
that refusal is the behavior under test, not an error in the injector.
"""

from __future__ import annotations

import time

import grpc

from protocol_tpu.faults.plan import FaultAction, FaultSchedule
from protocol_tpu.utils.lockwitness import make_lock


class FaultInjectedError(grpc.RpcError):
    """The client-side injector's stand-in for a transport failure —
    quacks like a live RpcError (``code()``/``details()``) so the
    production retry ladder handles it without knowing chaos exists."""

    def __init__(self, code=grpc.StatusCode.UNAVAILABLE,
                 details: str = "chaos: injected fault"):
        super().__init__(details)
        self._code = code
        self._details = details

    def code(self):
        return self._code

    def details(self):
        return self._details


_F32_NAN = b"\x00\x00\xc0\x7f"  # little-endian float32 quiet NaN


def corrupt_request(request, schedule: FaultSchedule, site: str,
                    method: str, index: int):
    """Deterministically poison a COPY of ``request`` such that the
    server's decode hardening MUST refuse it (the refusal path is what
    this fault class drills — a flip that decodes to a valid finite
    value would silently APPLY and poison the arena, the exact outcome
    the contract forbids): a float column gets one deterministic lane
    overwritten with NaN bytes; a message carrying only integer blobs
    gets its first blob sheared by a byte (size mismatch at unblob).
    Returns the corrupted copy, or None when the message carries no
    blob bytes at all (an empty delta)."""
    mutated = type(request)()
    mutated.CopyFrom(request)
    float_blobs, int_blobs = [], []
    for batch_name in ("providers", "requirements"):
        if mutated.HasField(batch_name):
            for nt in getattr(mutated, batch_name).columns:
                if len(nt.tensor.data):
                    (
                        float_blobs if nt.tensor.dtype == "float32"
                        else int_blobs
                    ).append(nt.tensor)
    fields = type(mutated).DESCRIPTOR.fields_by_name
    for blob_name in ("provider_rows", "task_rows"):
        if blob_name in fields and mutated.HasField(blob_name):
            b = getattr(mutated, blob_name)
            if len(b.data):
                int_blobs.append(b)
    if float_blobs and len(float_blobs[0].data) >= 4:
        target = float_blobs[0]
        off, _ = schedule.corrupt_byte(
            site, method, index, len(target.data)
        )
        lane = (off // 4) % (len(target.data) // 4)
        raw = bytearray(target.data)
        raw[lane * 4:lane * 4 + 4] = _F32_NAN
        target.data = bytes(raw)
        return mutated
    if int_blobs:
        target = int_blobs[0]
        target.data = target.data[:-1]  # size mismatch at unblob
        return mutated
    return None


class ChaosClient:
    """``SchedulerBackendClient`` wrapper applying the schedule's
    client-side faults per call. Interface-compatible with the subset
    the session drivers use (``open_session`` / ``assign_delta`` /
    ``assign_v2`` / ``assign`` / ``health`` / ``close``).

    Fault semantics per call:

      drop       deliver-or-not is decided by one extra schedule bit:
                 half the drops never reach the server (request lost),
                 half reach it and lose the RESPONSE — the server
                 processed the tick, so the retry MUST be answered
                 idempotently, not re-applied.
      delay      sleep ``delay_ms`` before sending.
      corrupt    poison one TensorBlob in a copy (NaN lane / sheared
                 blob); the server must refuse at decode
                 (INVALID_ARGUMENT).
      truncate   OpenSession only: the final chunk is withheld, so the
                 server sees a short stream and refuses.
      duplicate  AssignDelta only: the same request is sent twice
                 back-to-back; the second answer must be the replayed
                 twin of the first (``counters["dup_mismatch"]`` counts
                 violations).
    """

    def __init__(self, client, schedule: FaultSchedule,
                 site: str = "client"):
        self._client = client
        self._schedule = schedule
        self._site = site
        self._lock = make_lock("chaos")
        self._index: dict[str, int] = {}
        self.counters: dict[str, int] = {}

    # ---------------- bookkeeping ----------------

    def _next(self, method: str) -> int:
        with self._lock:
            i = self._index.get(method, 0)
            self._index[method] = i + 1
            return i

    def _count(self, name: str) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + 1

    def _act(self, method: str) -> tuple[FaultAction, int]:
        i = self._next(method)
        return self._schedule.decide(self._site, method, i), i

    def _drop_after(self, method: str, index: int) -> bool:
        # one extra deterministic bit: False = request lost before the
        # server, True = server processed and the response was lost
        return FaultSchedule._frac(
            self._schedule.config.seed, "drop_after", self._site, method,
            index,
        ) < 0.5

    # ---------------- faulted calls ----------------

    def _unary(self, method: str, send, request):
        act, i = self._act(method)
        if act.delay_ms:
            self._count("delay")
            time.sleep(act.delay_ms / 1e3)
        if act.drop:
            if self._drop_after(method, i):
                send(request)  # the server sees it; the answer dies
                self._count("drop_response")
            else:
                self._count("drop_request")
            raise FaultInjectedError()
        # directional partition faults: unlike the symmetric drop's
        # coin flip, these sever exactly ONE direction — drop_request
        # loses the call before the server (A→B cut), drop_response
        # lets the server PROCESS it and kills only the answer (B→A
        # cut: the retry must be served the replayed twin, never
        # re-applied)
        if act.drop_request:
            self._count("drop_request")
            raise FaultInjectedError()
        if act.drop_response:
            send(request)
            self._count("drop_response")
            raise FaultInjectedError()
        if act.corrupt:
            mutated = corrupt_request(
                request, self._schedule, self._site, method, i
            )
            if mutated is not None:
                self._count("corrupt")
                return send(mutated)
        if act.duplicate and method == "AssignDelta":
            self._count("duplicate")
            first = send(request)
            second = send(request)
            if (
                first.session_ok and second.session_ok
                and first.result.provider_for_task.data
                != second.result.provider_for_task.data
            ):
                # a duplicated tick that produced a DIFFERENT plan was
                # double-applied — the exact bug dedup exists to refuse
                self._count("dup_mismatch")
            return first
        return send(request)

    def assign_delta(self, request, timeout=60.0, metadata=None):
        return self._unary(
            "AssignDelta",
            lambda req: self._client.assign_delta(
                req, timeout=timeout, metadata=metadata
            ),
            request,
        )

    def assign_v2(self, request, timeout=60.0, metadata=None):
        return self._unary(
            "AssignV2",
            lambda req: self._client.assign_v2(
                req, timeout=timeout, metadata=metadata
            ),
            request,
        )

    def assign(self, request, timeout=60.0, metadata=None):
        return self._unary(
            "Assign",
            lambda req: self._client.assign(
                req, timeout=timeout, metadata=metadata
            ),
            request,
        )

    def open_session(self, chunks, timeout=300.0, metadata=None):
        act, i = self._act("OpenSession")
        if act.delay_ms:
            self._count("delay")
            time.sleep(act.delay_ms / 1e3)
        if act.drop or act.drop_request:
            # a streamed call's drop is always request-side: losing the
            # response of a half-open stream presents as UNAVAILABLE
            # either way
            self._count("drop_request")
            raise FaultInjectedError()
        if act.drop_response:
            self._client.open_session(
                iter(list(chunks)), timeout=timeout, metadata=metadata
            )
            self._count("drop_response")
            raise FaultInjectedError()
        chunk_list = list(chunks)
        if act.truncate and len(chunk_list) > 0:
            self._count("truncate")
            if len(chunk_list) > 1:
                chunk_list = chunk_list[:-1]
            else:
                # single-chunk snapshot: shear the payload instead
                short = type(chunk_list[0])()
                short.CopyFrom(chunk_list[0])
                short.payload = short.payload[: max(
                    1, len(short.payload) // 2
                )]
                chunk_list = [short]
        return self._client.open_session(
            iter(chunk_list), timeout=timeout, metadata=metadata
        )

    def health(self, timeout=10.0):
        return self._client.health(timeout=timeout)

    def close(self) -> None:
        self._client.close()

    # reconnect support: the harness's retry ladder replaces the inner
    # client on transport failure, keeping the fault counters/cursors
    @property
    def address(self) -> str:
        return self._client.address

    def rebind(self, client) -> None:
        old, self._client = self._client, client
        try:
            old.close()
        except Exception:
            pass


class ChaosServerInterceptor(grpc.ServerInterceptor):
    """Server-side drop/delay by method, one decision per RPC. Wraps
    whichever handler shape the method uses (unary-unary or
    stream-unary — the seam's two shapes); other shapes pass through.

    ``proc_id`` arms the SLOW-NODE gray failure: when this process is
    the config's ``slow_proc`` target (proc id ``p<K>``), every RPC's
    response is inflated by ``slow_ms`` at ``slow_rate`` — the node
    stays alive and correct, just too slow. The failure detector must
    classify it SUSPECT (its sessions degrade under the
    bounded-staleness watchdog), never DEAD — flap suppression is what
    keeps a merely-slow node in the fleet."""

    def __init__(self, schedule: FaultSchedule, site: str = "server",
                 proc_id: str = "p0"):
        self._schedule = schedule
        self._site = site
        self._proc_id = str(proc_id)
        self._lock = make_lock("chaos")
        self._index: dict[str, int] = {}
        self.counters: dict[str, int] = {}

    def _next(self, method: str) -> int:
        with self._lock:
            i = self._index.get(method, 0)
            self._index[method] = i + 1
            return i

    def _count(self, name: str) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + 1

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method.rsplit("/", 1)[-1]
        i = self._next(method)
        act = self._schedule.decide(self._site, method, i)
        cfg = self._schedule.config
        slow_ms = 0.0
        if (
            cfg.slow_proc is not None
            and self._proc_id == f"p{int(cfg.slow_proc)}"
            and FaultSchedule._frac(
                cfg.seed, "slow", self._site, method, i
            ) < cfg.slow_rate
        ):
            slow_ms = cfg.slow_ms
        if not (act.drop or act.delay_ms or slow_ms):
            return handler

        def wrap(inner):
            def faulted(request_or_iterator, context):
                if act.delay_ms:
                    self._count("delay")
                    time.sleep(act.delay_ms / 1e3)
                if slow_ms:
                    self._count("slow")
                    time.sleep(slow_ms / 1e3)
                if act.drop:
                    self._count("drop")
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "chaos: injected server-side drop",
                    )
                return inner(request_or_iterator, context)

            return faulted

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                wrap(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.stream_unary is not None:
            return grpc.stream_unary_rpc_method_handler(
                wrap(handler.stream_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler
