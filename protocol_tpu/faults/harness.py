"""The seeded chaos drill: one recorded trace, one session, real faults.

``run_chaos`` drives a recorded (or synthesized) trace through a live
loopback servicer while the fault plane fires — server-side drops and
delays (gRPC interceptor), client-side corruption / truncation /
duplication / lost responses (the :class:`ChaosClient` shim), a
scripted servicer kill+restart, a shard blackout, a forced eviction,
and the per-tick solve deadline — and reports what the recovery
machinery did about it.

The acceptance claim this harness exists to check is the strongest one
the trace subsystem can express (the VirtualFlow decoupling argument):
under kills, drops, delays and blackouts, the session must reconverge
**warm** — zero full-snapshot reopens — and every fresh (non-degraded)
tick's plan must be **bit-identical to the fault-free replay** of the
same trace. Degraded (stale) answers must be explicitly flagged and
bounded; a forced eviction is the one fault whose contract IS the
reopen (counted, not hidden).

The kill is staged as the worst case the checkpoint protocol must
survive: the tick is applied and flushed server-side, the RESPONSE is
discarded (as a crash would), the servicer is torn down and a fresh one
rehydrates from the checkpoint directory — the client's retransmit must
then be answered idempotently from the restored cursor, not refused
into a reopen.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Optional

import grpc
import numpy as np

from protocol_tpu.faults.inject import ChaosClient
from protocol_tpu.faults.plan import ChaosConfig, FaultSchedule


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Driver:
    """One session's chaos-hardened drive loop (the production ladder:
    transport retry + reconnect, RESOURCE_EXHAUSTED backoff-retry,
    INVALID_ARGUMENT resend, reopen only when the session is truly
    gone)."""

    def __init__(self, address: str, schedule: FaultSchedule,
                 sid: str, kernel: str, snap, max_retries: int = 60):
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        self.address = address
        self.sid = sid
        self.kernel = kernel
        self.snap = snap
        self.max_retries = max_retries
        self.client = ChaosClient(
            SchedulerBackendClient(address), schedule
        )
        self.fp: Optional[str] = None
        self.server_tick = 0
        self.counters = {
            "reopens": 0,
            "transport_retries": 0,
            "throttle_retries": 0,
            "corrupt_resends": 0,
            "stale_served": 0,
            "replayed_served": 0,
        }

    def _count(self, name: str) -> None:
        self.counters[name] += 1

    def reconnect(self) -> None:
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        self.client.rebind(SchedulerBackendClient(self.address))

    def open(self, p_cols, r_cols) -> np.ndarray:
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire
        from protocol_tpu.trace import format as tfmt

        snap = self.snap
        w = tfmt._as_ns(dict(zip(
            ("price", "load", "proximity", "priority"), snap.weights
        )))
        fp = wire.epoch_fingerprint(
            p_cols, r_cols, w, self.kernel,
            max(int(snap.top_k) or 64, 1), snap.eps, snap.max_iters,
        )
        req = pb.AssignRequestV2(
            providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
            requirements=wire.encode_requirements_v2(
                tfmt._as_ns(r_cols)
            ),
            weights=pb.CostWeights(
                price=snap.weights[0], load=snap.weights[1],
                proximity=snap.weights[2], priority=snap.weights[3],
            ),
            kernel=self.kernel, top_k=snap.top_k, eps=snap.eps,
            max_iters=snap.max_iters,
        )
        chunks = list(wire.chunk_snapshot(self.sid, fp, req))
        for attempt in range(self.max_retries):
            try:
                resp = self.client.open_session(
                    iter(chunks), timeout=300
                )
            except grpc.RpcError:
                self._count("transport_retries")
                time.sleep(0.01 * min(attempt + 1, 10))
                self.reconnect()
                continue
            if resp.ok:
                self.fp = fp
                self.server_tick = 0
                return wire.unblob(
                    resp.result.provider_for_task, np.int32
                )
            # truncated stream / draining: transient, re-send the
            # snapshot (the chaos twin of the matcher's unary fallback)
            self._count("transport_retries")
            time.sleep(0.01 * min(attempt + 1, 10))
        raise RuntimeError(
            f"OpenSession never succeeded after {self.max_retries} "
            "attempts"
        )

    def _delta_request(self, tick: int, delta):
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire
        from protocol_tpu.trace import format as tfmt

        req = pb.AssignDeltaRequest(
            session_id=self.sid, epoch_fingerprint=self.fp, tick=tick
        )
        if delta.provider_rows.size:
            req.provider_rows.CopyFrom(
                wire.blob(delta.provider_rows, np.int32)
            )
            req.providers.CopyFrom(
                wire.encode_providers_v2(tfmt._as_ns(delta.p_cols))
            )
        if delta.task_rows.size:
            req.task_rows.CopyFrom(wire.blob(delta.task_rows, np.int32))
            req.requirements.CopyFrom(
                wire.encode_requirements_v2(tfmt._as_ns(delta.r_cols))
            )
        return req

    def tick(self, delta, p_cols, r_cols) -> tuple[np.ndarray, bool]:
        """One delta tick through the ladder. Returns (p4t, stale)."""
        from protocol_tpu.proto import wire

        req = self._delta_request(self.server_tick + 1, delta)
        invalid_resent = False
        for attempt in range(self.max_retries):
            try:
                resp = self.client.assign_delta(req, timeout=300)
            except grpc.RpcError as e:
                if (
                    e.code() == grpc.StatusCode.INVALID_ARGUMENT
                    and not invalid_resent
                ):
                    # corrupted-in-transit frame refused at decode
                    # before any state moved: resend once
                    self._count("corrupt_resends")
                    invalid_resent = True
                    continue
                self._count("transport_retries")
                time.sleep(0.01 * min(attempt + 1, 10))
                self.reconnect()
                continue
            if resp.session_ok:
                self.server_tick += 1
                if resp.stale:
                    self._count("stale_served")
                if resp.replayed:
                    self._count("replayed_served")
                return (
                    wire.unblob(
                        resp.result.provider_for_task, np.int32
                    ),
                    bool(resp.stale),
                )
            if "RESOURCE_EXHAUSTED" in resp.error:
                # blackout / admission / backpressure: the session is
                # alive — retry the SAME tick after a short backoff
                self._count("throttle_retries")
                time.sleep(0.01 * min(attempt + 1, 10))
                continue
            # truly gone (evicted / unknown): reopen from the current
            # cumulative columns — the counted, last-resort rung
            self._count("reopens")
            p4t = self.open(p_cols, r_cols)
            return p4t, False
        raise RuntimeError(
            f"delta tick {self.server_tick + 1} never succeeded after "
            f"{self.max_retries} attempts"
        )

    def close(self) -> None:
        self.client.close()


def run_chaos(
    trace_path: str,
    kernel: Optional[str] = None,
    seed: int = 0,
    drop_rate: float = 0.0,
    delay_rate: float = 0.0,
    delay_ms: float = 2.0,
    corrupt_rate: float = 0.0,
    truncate_rate: float = 0.0,
    duplicate_rate: float = 0.0,
    kill_at_tick: Optional[int] = None,
    blackout_at_tick: Optional[int] = None,
    blackout_refusals: int = 2,
    evict_at_tick: Optional[int] = None,
    tick_deadline_ms: Optional[float] = None,
    max_stale_ticks: int = 2,
    ckpt_every: int = 1,
    shards: int = 2,
    ckpt_dir: Optional[str] = None,
) -> dict:
    """Run the drill. Returns the report dict; the perf gate asserts on
    it (this function only measures — policy lives in the gate)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from protocol_tpu.fleet.fabric import FleetConfig
    from protocol_tpu.services.scheduler_grpc import serve
    from protocol_tpu.trace import format as tfmt
    from protocol_tpu.trace.replay import iter_input_ticks, replay

    trace = tfmt.read_trace(trace_path)
    snap = trace.snapshot
    if snap is None:
        raise ValueError(f"{trace_path}: no snapshot frame")
    kernel = kernel or snap.kernel or "native-mt:1"

    # fault-free ground truth: the same trace through the in-process
    # arena (bit-identical to the wire path by the replay-identity gate)
    base = replay(
        trace_path, engine=kernel, verify=False, keep_p4t=True
    )
    baseline = base["p4ts"]

    config = ChaosConfig(
        seed=seed, drop_rate=drop_rate, delay_rate=delay_rate,
        delay_ms=delay_ms, corrupt_rate=corrupt_rate,
        truncate_rate=truncate_rate, duplicate_rate=duplicate_rate,
        kill_at_tick=kill_at_tick, blackout_shard=0,
        blackout_refusals=blackout_refusals,
        evict_at_tick=evict_at_tick,
    )
    schedule = FaultSchedule(config)

    tmpdir = None
    if ckpt_dir is None:
        tmpdir = tempfile.TemporaryDirectory(prefix="chaos_ckpt_")
        ckpt_dir = tmpdir.name
    fleet_cfg = FleetConfig(
        shards=shards, ckpt_dir=ckpt_dir, ckpt_every=ckpt_every,
        tick_deadline_ms=tick_deadline_ms,
        max_stale_ticks=max_stale_ticks,
    )
    port = _free_port()
    address = f"127.0.0.1:{port}"
    server = serve(address, fleet=fleet_cfg, chaos=schedule)
    sid = "t0@chaos"
    driver = _Driver(address, schedule, sid, kernel, snap)

    per_tick_identical: list[bool] = []
    stale_ticks: list[int] = []
    fresh_mismatch_ticks: list[int] = []
    assigned_frac_min = 1.0
    restarted = False
    try:
        for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
            if tick == 0:
                p4t, stale = driver.open(p_cols, r_cols), False
            else:
                if kill_at_tick is not None and tick == kill_at_tick:
                    # the worst-case crash window: the tick is applied
                    # and checkpointed server-side, the response dies,
                    # the servicer dies — the retransmit must be
                    # answered idempotently by the RESTART
                    req = driver._delta_request(
                        driver.server_tick + 1, delta
                    )
                    try:
                        driver.client.assign_delta(req, timeout=300)
                    except grpc.RpcError:
                        pass  # a chaos drop here is fine either way
                    server.stop(grace=None)
                    server = serve(
                        address, fleet=fleet_cfg, chaos=schedule
                    )
                    restarted = True
                    driver.reconnect()
                if (
                    blackout_at_tick is not None
                    and tick == blackout_at_tick
                ):
                    server.servicer.sessions.blackout(
                        server.servicer.sessions.shard_index(sid),
                        blackout_refusals,
                    )
                if evict_at_tick is not None and tick == evict_at_tick:
                    server.servicer.sessions.shard_of(sid).evict(
                        sid, "chaos"
                    )
                p4t, stale = driver.tick(delta, p_cols, r_cols)
            n_live = int(np.asarray(r_cols["valid"], bool).sum())
            if n_live > 0:
                assigned_frac_min = min(
                    assigned_frac_min,
                    float((p4t >= 0).sum()) / n_live,
                )
            if stale:
                stale_ticks.append(tick)
                per_tick_identical.append(False)
            else:
                same = bool(np.array_equal(p4t, baseline[tick]))
                per_tick_identical.append(same)
                if not same:
                    fresh_mismatch_ticks.append(tick)
        servicer = server.servicer
        seam = servicer.seam.snapshot()
        obs_snap = servicer.obs.snapshot()
        fleet_snap = servicer.sessions.snapshot()
    finally:
        driver.close()
        server.stop(grace=None)
        if tmpdir is not None:
            tmpdir.cleanup()

    ticks = len(per_tick_identical)
    return {
        "trace": trace_path,
        "kernel": kernel,
        "chaos": config.spec(),
        "ticks": ticks,
        "restarted": restarted,
        "client": dict(driver.counters),
        "injected": dict(driver.client.counters),
        "stale_ticks": stale_ticks,
        "assigned_frac_min": round(assigned_frac_min, 4),
        "max_stale_streak": _max_streak(stale_ticks),
        "fresh_ticks_identical": not fresh_mismatch_ticks,
        "fresh_mismatch_ticks": fresh_mismatch_ticks[:8],
        "final_tick_identical": (
            bool(per_tick_identical[-1]) if ticks else False
        ),
        "server_seam": {
            k: v for k, v in sorted(seam.items())
            if isinstance(v, (int, float)) and (
                "stale" in k or "replay" in k or "restore" in k
                or "reopen" in k or "tick_mismatch" in k
                or "deadline" in k or "drain" in k or "ckpt" in k
            )
        },
        "server_stale_obs": _stale_obs(obs_snap),
        "blackout_refusals_served": fleet_snap.get(
            "blackout_refusals_served", 0
        ),
    }


def _max_streak(stale_ticks: list) -> int:
    best = run = 0
    prev = None
    for t in stale_ticks:
        run = run + 1 if prev is not None and t == prev + 1 else 1
        best = max(best, run)
        prev = t
    return best


def _stale_obs(obs_snap: dict) -> dict:
    """Per-tenant stale-tick counters from the obs plane (degraded
    answers must be COUNTED, not just flagged — the acceptance bar)."""
    out = {}
    for tenant, entry in (obs_snap.get("tenants") or {}).items():
        n = entry.get("stale_ticks")
        if n:
            out[tenant] = n
    return out
