"""Deterministic chaos plane for the scheduler seam.

The reference protocol is built for constant partial failure (heartbeat
monitors, invite retries, node ejection); this package is the seam's
equivalent: a SEEDED, byte-replayable fault-injection plane wired into
the seams the repo already owns, plus the recovery machinery that makes
those faults survivable.

  * :mod:`protocol_tpu.faults.plan` — the fault schedule: a pure
    function of ``(seed, site, method, call index)`` deciding drops,
    delays, corruptions, truncations and duplications, plus scripted
    one-shot events (servicer kill, shard blackout, forced eviction).
    No ``random``, no clocks: the same seed replays the same chaos.
  * :mod:`protocol_tpu.faults.inject` — where faults land: a client-side
    RPC shim (drop / delay / corrupt TensorBlob bytes / truncate
    snapshot streams / duplicate deltas) and a server-side gRPC
    interceptor (drop / delay before the servicer).
  * :mod:`protocol_tpu.faults.checkpoint` — warm session checkpoints:
    per-session crash-atomic journals reusing the trace SNAPSHOT /
    OUTCOME / ARENA codecs, so a restarted servicer rehydrates sessions
    warm and ``AssignDelta`` resumes at the checkpointed cursor instead
    of refusing every client into a full-snapshot reopen herd.
  * :mod:`protocol_tpu.faults.harness` — the seeded chaos drill the CI
    gate runs: a recorded trace driven through kills, drops, delays and
    blackouts must reconverge with zero full-snapshot reopens and a
    final plan bit-identical to the fault-free replay.
"""

from protocol_tpu.faults.plan import ChaosConfig, FaultAction, FaultSchedule
from protocol_tpu.faults.checkpoint import SessionCheckpointer

__all__ = [
    "ChaosConfig",
    "FaultAction",
    "FaultSchedule",
    "SessionCheckpointer",
]
