"""Seeded deterministic fault schedule.

Every fault decision is a pure function of ``(seed, fault class, site,
method, call index)``: the same config replays the same chaos byte for
byte, so a chaos run can be re-executed for debugging and its acceptance
claims (reconvergence, bit-identical final plans) can be gated in CI the
same way perf claims are. The hash-to-fraction trick is the one the
client's retry jitter already uses (``RemoteBatchMatcher._backoff_s``):
sha1 bytes as a uniform draw — no ``random`` (drifts across library
versions), no clocks.

Rate faults (drop / delay / corrupt / truncate / duplicate) fire
independently per call with their configured probability. Scripted
faults (servicer kill, shard blackout, forced eviction, budget
starvation) are one-shot events keyed on a tick index and are owned by
the DRIVER (harness / loadgen), not the injectors — a process kill is
not something an interceptor can do to itself cleanly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import NamedTuple, Optional

ENV_VAR = "PROTOCOL_TPU_CHAOS"


class FaultAction(NamedTuple):
    """What one call suffers. ``delay_ms == 0`` means no delay.

    ``drop`` is the symmetric transport loss (an extra schedule bit
    splits it request/response-side at the injector). ``drop_request``
    and ``drop_response`` are the DIRECTIONAL knobs behind the
    asymmetric-partition site: with only ``drop_response_rate`` set,
    requests flow and answers die (A→B flows while B→A drops) — the
    half-open failure that drills the idempotent-retransmit dedup,
    never the reopen rung."""

    drop: bool
    delay_ms: float
    corrupt: bool
    truncate: bool
    duplicate: bool
    drop_request: bool = False
    drop_response: bool = False

    @property
    def clean(self) -> bool:
        return not (
            self.drop or self.delay_ms or self.corrupt
            or self.truncate or self.duplicate
            or self.drop_request or self.drop_response
        )


NO_FAULT = FaultAction(False, 0.0, False, False, False)


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Declarative chaos knobs. All-zero (the default) is inert.

    ``from_spec`` parses the compact ``key=value,key=value`` form the
    env var and CLI flags carry, e.g.::

        seed=3,drop=0.05,delay=0.05,delay_ms=5,corrupt=0.01,
        kill_at_tick=4,blackout_shard=1,blackout_refusals=2
    """

    seed: int = 0
    drop_rate: float = 0.0
    delay_rate: float = 0.0
    delay_ms: float = 5.0
    corrupt_rate: float = 0.0
    truncate_rate: float = 0.0
    duplicate_rate: float = 0.0
    # event-stream chaos (the "events" site): reorder_rate displaces an
    # event a few delivery slots later — combined with drop (delivered
    # late = the retransmit ladder) and duplicate at the same site, it
    # drills the stream engine's per-source seq dedup: a chaos'd event
    # stream must CONVERGE (final reconcile bit-identical to fault-free
    # delivery), with double-applies impossible by construction
    reorder_rate: float = 0.0
    reorder_span: int = 4
    # directional (gray) partition faults: request-side loss severs
    # A→B while answers still flow; response-side loss is the
    # asymmetric partition the retransmit-dedup ladder exists for —
    # the server APPLIES the tick, the answer dies, and the resend
    # must be served the replayed twin, never re-applied
    drop_request_rate: float = 0.0
    drop_response_rate: float = 0.0
    # slow-node gray failure: ONE fleet process (``slow_proc``, by
    # index — proc id "p<K>") inflates every response by ``slow_ms``
    # at ``slow_rate`` — alive, answering, and too slow, the failure
    # mode the detector must classify SUSPECT (degrade, don't eject)
    slow_proc: Optional[int] = None
    slow_rate: float = 1.0
    slow_ms: float = 25.0
    # scripted one-shot events (driver-owned; see module docstring)
    kill_at_tick: Optional[int] = None
    blackout_shard: Optional[int] = None
    blackout_refusals: int = 2
    evict_at_tick: Optional[int] = None
    starve_budget_ticks: int = 0
    # process-level scripted targets (dfleet): which fleet PROCESS dies
    # (SIGKILL — the crash drill) or live-migrates (Migrate RPC — the
    # rolling-upgrade drill) once every session has passed the tick.
    # Owned by the multi-process driver (fleet/loadgen --processes /
    # dfleet.manager), exactly like kill_at_tick is owned by the
    # single-process harness — a process cannot kill -9 itself cleanly.
    kill_proc_at_tick: Optional[int] = None
    kill_proc: int = 1
    migrate_at_tick: Optional[int] = None
    migrate_proc: int = 1
    # SIGSTOP/SIGCONT pause (the zombie-resume drill): the target
    # process is frozen — not dead — once every session passed the
    # tick; the detector must eject it autonomously, and the resumed
    # zombie must find its journal fence superseded. Driver-owned like
    # every process-level event (a process cannot pause itself and
    # still be the thing under test).
    pause_proc_at_tick: Optional[int] = None
    pause_proc: int = 1

    _FLOATS = (
        "drop_rate", "delay_rate", "delay_ms", "corrupt_rate",
        "truncate_rate", "duplicate_rate", "reorder_rate",
        "drop_request_rate", "drop_response_rate",
        "slow_rate", "slow_ms",
    )
    _INTS = (
        "seed", "kill_at_tick", "blackout_shard", "blackout_refusals",
        "evict_at_tick", "starve_budget_ticks",
        "kill_proc_at_tick", "kill_proc",
        "migrate_at_tick", "migrate_proc",
        "slow_proc", "pause_proc_at_tick", "pause_proc",
        "reorder_span",
    )
    # spec aliases: the short names the env/CLI spec uses
    _ALIASES = {
        "drop": "drop_rate",
        "delay": "delay_rate",
        "corrupt": "corrupt_rate",
        "truncate": "truncate_rate",
        "dup": "duplicate_rate",
        "dropreq": "drop_request_rate",
        "dropresp": "drop_response_rate",
        "reorder": "reorder_rate",
    }

    def active(self) -> bool:
        return bool(
            self.drop_rate or self.delay_rate or self.corrupt_rate
            or self.truncate_rate or self.duplicate_rate
            or self.reorder_rate
            or self.drop_request_rate or self.drop_response_rate
            or self.slow_proc is not None
            or self.kill_at_tick is not None
            or self.blackout_shard is not None
            or self.evict_at_tick is not None
            or self.starve_budget_ticks
            or self.kill_proc_at_tick is not None
            or self.migrate_at_tick is not None
            or self.pause_proc_at_tick is not None
        )

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosConfig":
        kv: dict = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"chaos spec item {part!r} is not key=value"
                )
            key, _, val = part.partition("=")
            key = cls._ALIASES.get(key.strip(), key.strip())
            if key in cls._FLOATS:
                kv[key] = float(val)
            elif key in cls._INTS:
                kv[key] = int(val)
            else:
                raise ValueError(f"unknown chaos knob {key!r}")
        return cls(**kv)

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> Optional["ChaosConfig"]:
        e = os.environ if env is None else env
        spec = e.get(ENV_VAR, "").strip()
        return cls.from_spec(spec) if spec else None

    def spec(self) -> str:
        """The compact round-trippable form (provenance for reports)."""
        parts = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v != f.default:
                parts.append(f"{f.name}={v}")
        return ",".join(parts)


class FaultSchedule:
    """The deterministic decision engine over a :class:`ChaosConfig`.

    ``decide(site, method, index)`` answers "what does call number
    ``index`` of ``method`` at ``site`` suffer?" — a pure function, so
    injectors on both sides of the wire can share one config without
    sharing state, and a replayed run sees the identical fault train.
    """

    def __init__(self, config: ChaosConfig):
        self.config = config

    @staticmethod
    def _frac(seed: int, salt: str, site: str, method: str,
              index: int) -> float:
        digest = hashlib.sha1(
            f"{seed}:{salt}:{site}:{method}:{index}".encode()
        ).digest()
        return int.from_bytes(digest[:8], "big") / 2.0 ** 64

    def decide(self, site: str, method: str, index: int) -> FaultAction:
        c = self.config
        f = self._frac
        drop = c.drop_rate > 0 and f(
            c.seed, "drop", site, method, index
        ) < c.drop_rate
        delay = (
            c.delay_ms
            if c.delay_rate > 0
            and f(c.seed, "delay", site, method, index) < c.delay_rate
            else 0.0
        )
        corrupt = c.corrupt_rate > 0 and f(
            c.seed, "corrupt", site, method, index
        ) < c.corrupt_rate
        truncate = c.truncate_rate > 0 and f(
            c.seed, "truncate", site, method, index
        ) < c.truncate_rate
        duplicate = c.duplicate_rate > 0 and f(
            c.seed, "dup", site, method, index
        ) < c.duplicate_rate
        drop_request = c.drop_request_rate > 0 and f(
            c.seed, "dropreq", site, method, index
        ) < c.drop_request_rate
        drop_response = c.drop_response_rate > 0 and f(
            c.seed, "dropresp", site, method, index
        ) < c.drop_response_rate
        return FaultAction(
            drop, delay, corrupt, truncate, duplicate,
            drop_request, drop_response,
        )

    def reorder_slots(self, site: str, method: str, index: int) -> int:
        """Deterministic delivery displacement for a reorder fault: 0 =
        in order, else 1..reorder_span slots late. Same pure-function
        contract as :meth:`decide`."""
        c = self.config
        if c.reorder_rate <= 0:
            return 0
        if self._frac(
            c.seed, "reorder", site, method, index
        ) >= c.reorder_rate:
            return 0
        span = max(int(c.reorder_span), 1)
        return 1 + int(
            self._frac(c.seed, "reorder-span", site, method, index) * span
        )

    def corrupt_byte(self, site: str, method: str, index: int,
                     n_bytes: int) -> tuple[int, int]:
        """Deterministic (offset, xor-mask) for a corruption fault —
        which byte of the payload flips, and how. The mask is never 0
        (a corruption that changes nothing is not a fault)."""
        digest = hashlib.sha1(
            f"{self.config.seed}:cbyte:{site}:{method}:{index}".encode()
        ).digest()
        off = int.from_bytes(digest[:8], "big") % max(n_bytes, 1)
        mask = digest[8] or 0xFF
        return off, mask


def event_delivery_order(
    schedule: FaultSchedule, n_events: int, site: str = "events"
) -> list:
    """Chaos'd-but-CONVERGENT delivery order for an event stream: the
    deterministic composition of the transport faults at the ``events``
    site with the retransmit ladder the sources already run.

    Per original event index ``i`` (emission order):

      * drop      -> the first delivery dies; the source retransmits,
                     landing ``reorder_span + 1`` slots later (the ack
                     timeout's worth of stream progress)
      * reorder   -> delivered 1..reorder_span slots late (overtaken by
                     newer events — the dedup ladder supersedes it)
      * duplicate -> a second copy lands ``reorder_span`` slots after
                     the first (a retransmit whose original survived)

    Every event index appears at least once (nothing is lost forever —
    convergence is by construction, exactly what the retransmit ladder
    guarantees), and the whole order is a pure function of the seeded
    schedule: a chaos replay sees the identical delivery train.
    Returns the list of event indices in delivery order (duplicates
    appear twice)."""
    span = max(int(schedule.config.reorder_span), 1)
    entries: list = []
    for i in range(n_events):
        action = schedule.decide(site, "event", i)
        pos = float(i)
        if action.drop:
            pos = i + span + 1 + 0.5
        else:
            late = schedule.reorder_slots(site, "event", i)
            if late:
                pos = i + late + 0.25
        entries.append((pos, i))
        if action.duplicate:
            entries.append((pos + span + 0.75, i))
    entries.sort()
    return [i for _, i in entries]
