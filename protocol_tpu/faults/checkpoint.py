"""Warm session checkpoints: crash-safe per-session journals.

A servicer crash used to destroy every session arena: H clients would
stampede into cold full-snapshot reopens (the herd the fallback ladder
exists to avoid, amplified H-fold at the worst possible moment). The
checkpointer gives each session a compact on-disk twin, flushed on a
tick cadence BEFORE the tick's response is acknowledged, so a restarted
servicer rehydrates every session warm and ``AssignDelta`` resumes at
the checkpointed cursor.

One file per session, reusing the trace container and codecs verbatim
(``PTTRACE1`` framing, SNAPSHOT = the session's padded columns as the
wire's own ``AssignRequestV2``, OUTCOME = the last acknowledged plan,
ARENA = the carried solver state via ``pack_arrays``):

    META      JSON: session identity + solve params + tick cursor +
              dedup CRC + arena cadence cursors
    SNAPSHOT  the session's CURRENT cumulative columns (padded, with
              the valid mask — bit-exact restore, no re-padding drift)
    ARENA     candidate structure + duals + previous matching
              (``NativeSolveArena.export_state``): the candidate lists
              are PATH-DEPENDENT (incremental merges reorder them), so
              without this frame a restart could only continue cold —
              with it, the restored warm chain is bit-identical to the
              uninterrupted one
    OUTCOME   tick + the last plan the client was (or was about to be)
              acknowledged — what idempotent retransmit replays

Writes are crash-atomic (temp file + ``os.replace``) and frames are
individually CRC'd, so a kill mid-flush leaves either the previous
intact checkpoint or a torn temp file nobody reads. A checkpoint that
fails to load (torn, version drift, decode error) is SKIPPED with a
warning: the session's client falls back down the ladder exactly as it
would have without checkpoints — recovery is an optimization, never a
new failure mode.

Cadence: ``every=1`` (the default, and what the chaos gate runs)
checkpoints every tick — the zero-reopen guarantee. ``every=N`` trades
durability for throughput: a crash loses up to N-1 ticks and the
affected clients re-open from their authoritative columns (counted,
bounded, explicit).

Namespacing (dfleet): journals are keyed by **(process id, session
id)** — every checkpointer owns ``<root>/<proc_id>/`` and only ever
reads its own namespace, so N servicer processes can share one journal
root (a shared volume) without ever rehydrating each other's live
sessions. Migration rides on this: :meth:`handoff` atomically renames a
journal from this process's namespace into the target's (``os.replace``
— the journal exists in exactly one namespace at every instant), and
the target rehydrates it warm on its next delta miss
(:meth:`load_one`). The post-load ownership re-check closes the
POSIX-fd window where a reader that opened the file just before the
rename could otherwise rehydrate a journal it no longer owns.

Fencing (ISSUE 14): each namespace carries a monotonic **fencing
epoch** (``FENCE.json``, stamped by the fleet manager at spawn and
SUPERSEDED at ejection/orphan-handoff). The checkpointer adopts the
stamp it finds at boot and re-reads the file (stat-cached — one
``os.stat`` per check) on every flush: a higher epoch on disk means
this process was EJECTED while it wasn't looking (SIGSTOP zombie,
partitioned node) and its journals re-routed — the flush REFUSES
(counted), and the servicer answers ``moved:`` instead of acking, so a
resumed zombie can never double-apply a tick or resurrect a journal it
no longer owns. Split-brain is impossible by construction: the PR 12
rule "the journal's location is the authority" becomes "…at the
highest fence". The stamp carries the post-ejection topology so the
zombie's redirects point at each session's REAL new home.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Optional

import numpy as np

from protocol_tpu.trace import format as tfmt

log = logging.getLogger(__name__)

_META_KIND = "session-checkpoint"
_SUFFIX = ".ckpt"
FENCE_NAME = "FENCE.json"


def fence_path(root: str, proc_id: str) -> str:
    return os.path.join(root, str(proc_id), FENCE_NAME)


def read_fence(root: str, proc_id: str) -> dict:
    """The namespace's current fence stamp: ``{"epoch": int,
    "topology": dict | None}``. Epoch 0 when no stamp exists (the
    pre-dfleet single-process layout) — fencing is inert there."""
    try:
        with open(fence_path(root, proc_id)) as fh:
            d = json.load(fh)
        return {
            "epoch": int(d.get("epoch", 0)),
            "topology": d.get("topology"),
        }
    except (OSError, ValueError):
        return {"epoch": 0, "topology": None}


def stamp_fence(
    root: str,
    proc_id: str,
    epoch: Optional[int] = None,
    topology: Optional[dict] = None,
) -> int:
    """Write the namespace's fence stamp (crash-atomic: temp +
    ``os.replace``). ``epoch=None`` bumps monotonically from whatever
    is on disk — the spawn/ejection callers never need to coordinate a
    counter, the file IS the counter. Returns the stamped epoch."""
    if epoch is None:
        epoch = read_fence(root, proc_id)["epoch"] + 1
    path = fence_path(root, proc_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"epoch": int(epoch), "topology": topology}, fh)
    os.replace(tmp, path)
    return int(epoch)


def _fname(session_id: str) -> str:
    # session ids are tenant-chosen strings: hash to a fixed-width safe
    # filename (the id itself rides in META)
    return hashlib.sha1(session_id.encode()).hexdigest()[:24] + _SUFFIX


def journal_session_id(path: str) -> Optional[str]:
    """Session id recorded in a journal's META frame (None when the
    file is torn/foreign) — what a dead process's orphaned journals are
    re-routed by (the filename is a hash; the id itself rides in META)."""
    try:
        for kind, payload in tfmt.read_frames(path):
            if kind == tfmt.KIND_META:
                meta = json.loads(payload)
                if meta.get("kind") == _META_KIND:
                    return meta.get("session_id")
                return None
            break  # META is always the first frame
    except Exception:
        return None
    return None


class SessionCheckpointer:
    """Per-session checkpoint writer/loader over ``<root>/<proc_id>/``
    (one namespace per servicer process; see the module docstring)."""

    def __init__(self, directory: str, every: int = 1,
                 proc_id: str = "p0"):
        self.root = directory
        self.proc_id = str(proc_id)
        self.directory = os.path.join(directory, self.proc_id)
        self.every = max(1, int(every))
        os.makedirs(self.directory, exist_ok=True)
        # fence adoption: cache the epoch the manager stamped before
        # spawning us (0 when unstamped — standalone layouts are inert).
        # A HIGHER epoch appearing on disk later means we were ejected.
        self.fence_epoch = read_fence(self.root, self.proc_id)["epoch"]
        self._fence_file = fence_path(self.root, self.proc_id)
        self._fence_cache: tuple = (None, {
            "epoch": self.fence_epoch, "topology": None,
        })
        # obs counters (scraped via the servicer's seam metrics)
        self.flushes = 0
        self.flush_failures = 0
        self.handoffs = 0
        self.fence_refusals = 0
        self.journals_skipped = 0

    def path_for(self, session_id: str) -> str:
        return os.path.join(self.directory, _fname(session_id))

    def peer_path(self, session_id: str, proc_id: str) -> str:
        """Where ``session_id``'s journal lives in ANOTHER process's
        namespace under the same root (the handoff target)."""
        return os.path.join(self.root, str(proc_id), _fname(session_id))

    # ---------------- fencing ----------------

    def fence_state(self) -> dict:
        """The namespace's CURRENT on-disk fence stamp, stat-cached (a
        check costs one ``os.stat`` unless the file changed). Benign
        under concurrency: the cache tuple swaps atomically and the
        worst case is one redundant re-read."""
        try:
            st = os.stat(self._fence_file)
            sig: Optional[tuple] = (st.st_mtime_ns, st.st_size)
        except OSError:
            sig = None
        cached_sig, cached = self._fence_cache
        if sig != cached_sig:
            cached = read_fence(self.root, self.proc_id)
            self._fence_cache = (sig, cached)
        return cached

    def fence_superseded(self) -> bool:
        """True when a HIGHER fence epoch was stamped into this
        namespace than the one this process adopted at boot: we were
        ejected (detector, orphan handoff) and must neither flush nor
        ack — the journals belong to the ring's survivors now."""
        return self.fence_state()["epoch"] > self.fence_epoch

    def due(self, tick: int) -> bool:
        """Is ``tick`` on the flush cadence? Tick 0 (the snapshot
        solve) always checkpoints — a crash before the first delta must
        still restore the session."""
        return tick == 0 or tick % self.every == 0

    # ---------------- write ----------------

    def flush_locked(self, session) -> bool:
        """Write the session's checkpoint (caller holds
        ``session.lock`` — the state must be a consistent tick). Best
        effort: a failed flush warns and counts, never fails the RPC;
        the cost is one potential reopen after a crash.

        A SUPERSEDED FENCE refuses outright (counted separately): an
        ejected process writing into a namespace whose journals were
        re-routed would resurrect state a survivor already owns — the
        exact split-brain the fence exists to make impossible."""
        if self.fence_superseded():
            self.fence_refusals += 1
            return False
        try:
            self._write_locked(session)
            self.flushes += 1
            return True
        except Exception:
            self.flush_failures += 1
            log.warning(
                "session checkpoint flush failed for %s",
                session.session_id, exc_info=True,
            )
            return False

    def _write_locked(self, session) -> None:
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire

        state = session.arena.export_state()
        meta = {
            "kind": _META_KIND,
            "session_id": session.session_id,
            "fingerprint": session.fingerprint,
            "kernel": session.kernel,
            "threads": int(session.threads),
            "top_k": int(session.top_k),
            "weights": [
                float(session.weights.price),
                float(session.weights.load),
                float(session.weights.proximity),
                float(session.weights.priority),
            ],
            "n_providers": int(session.n_providers),
            "n_tasks": int(session.n_tasks),
            "tick": int(session.tick),
            "last_delta_crc": int(session.last_delta_crc),
            "delta_rows_total": int(session.delta_rows_total),
        }
        if session.stream is not None:
            # the FULL stream state travels with the journal (ISSUE
            # 20): config + per-source dedup cursors + the reconcile-
            # cadence cursor + obs counters. The wire tick/CRC cursor
            # only dedups a resend of the LAST tick — a chaos'd
            # retransmit arriving as a FRESH tick after a migration
            # handoff would double-apply without the seq cursors at
            # the target. The gap tracker / divergence baseline are
            # rebased exactly from the restored arena at re-arm.
            meta["stream"] = session.stream.export_state()
        if state is not None:
            meta["arena"] = {
                "warm_solves": state.pop("warm_solves"),
                "dual_age": state.pop("dual_age"),
                "weights_key": list(state.pop("weights_key")),
                # float-pipeline provenance (string scalar — rides the
                # JSON meta, not the array pack); restore_state cold
                # re-grounds on a mismatched-ISA load
                "native_isa": state.pop("native_isa", "scalar"),
            }
        req = pb.AssignRequestV2(
            providers=wire.encode_providers_v2(
                tfmt._as_ns(session.p_cols)
            ),
            requirements=wire.encode_requirements_v2(
                tfmt._as_ns(session.r_cols)
            ),
            kernel=session.kernel,
            top_k=session.top_k,
        )
        final = self.path_for(session.session_id)
        tmp = final + ".tmp"
        writer = tfmt.TraceWriter(tmp, meta=meta)
        try:
            writer.write_snapshot(
                session.session_id, session.fingerprint, req
            )
            if state is not None:
                writer.write_arena(state)
            if session.last_p4t is not None:
                writer.write_outcome(
                    int(session.tick),
                    np.asarray(session.last_p4t, np.int32),
                )
        finally:
            writer.close()
        os.replace(tmp, final)

    # ---------------- migration handoff ----------------

    def handoff(self, session_id: str, dst_proc_id: str) -> bool:
        """Atomically move ``session_id``'s journal from this process's
        namespace into ``dst_proc_id``'s (``os.replace`` — same
        filesystem, so the journal exists in exactly one namespace at
        every instant: two processes can never BOTH rehydrate it).
        False = no journal to move (never flushed, or already handed
        off)."""
        src = self.path_for(session_id)
        dst = self.peer_path(session_id, dst_proc_id)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        try:
            os.replace(src, dst)
        except OSError:
            return False
        self.handoffs += 1
        return True

    # ---------------- read ----------------

    def load_all(self, budget=None, limit: Optional[int] = None) -> list:
        """Rehydrate the loadable checkpoints in the directory into
        fresh :class:`SolveSession` objects (sorted by session id for a
        deterministic restore order). ``limit`` caps the restore at the
        N most-recently-flushed files (the caller's session budget —
        restoring more would make the store's LRU pressure evict the
        sessions just restored). Unloadable files are skipped with a
        warning — the affected client re-opens down the ladder."""
        out = []
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.endswith(_SUFFIX)
            )
        except OSError:
            return out
        if limit is not None and len(names) > limit:
            def _mtime(name: str) -> float:
                try:
                    return os.path.getmtime(
                        os.path.join(self.directory, name)
                    )
                except OSError:
                    return 0.0

            skipped = len(names) - limit
            names = sorted(
                sorted(names, key=_mtime)[-limit:]
            )
            log.warning(
                "checkpoint restore capped at %d sessions "
                "(%d older files skipped)", limit, skipped,
            )
        loaded = []
        for name in names:
            path = os.path.join(self.directory, name)
            try:
                loaded.append(self._load(path, budget))
            except Exception:
                # torn META/frames (killed mid-flush), version drift,
                # decode error: COUNTED skip, never a failed restore —
                # the affected client re-opens down the ladder
                self.journals_skipped += 1
                log.warning(
                    "skipping unloadable session checkpoint %s", path,
                    exc_info=True,
                )
        loaded.sort(key=lambda s: s.session_id)
        out.extend(loaded)
        return out

    def _load(self, path: str, budget):
        from protocol_tpu.fleet import estimate_arena_bytes
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.services.session_store import (
            SolveSession,
            make_solve_arena,
            parse_session_kernel,
        )

        meta: Optional[dict] = None
        snapshot = None
        arena_state: Optional[dict] = None
        outcome = None
        for kind, payload in tfmt.read_frames(path):
            if kind == -1:
                raise ValueError(f"{path}: torn checkpoint tail")
            if kind == tfmt.KIND_META:
                meta = json.loads(payload)
            elif kind == tfmt.KIND_SNAPSHOT:
                snapshot = tfmt._parse_snapshot(payload)
            elif kind == tfmt.KIND_ARENA:
                arena_state = tfmt.unpack_arrays(payload)
            elif kind == tfmt.KIND_OUTCOME:
                outcome = tfmt._parse_outcome(payload)
        if meta is None or meta.get("kind") != _META_KIND:
            raise ValueError(f"{path}: not a session checkpoint")
        if snapshot is None:
            raise ValueError(f"{path}: checkpoint has no snapshot frame")
        parsed = parse_session_kernel(meta["kernel"])
        if parsed is None:
            raise ValueError(
                f"{path}: checkpointed kernel {meta['kernel']!r} is not "
                "session-servable"
            )
        engine, _ = parsed
        threads = int(meta["threads"])
        arena = make_solve_arena(
            engine, k=int(meta["top_k"]), threads=threads
        )
        p_cols, r_cols = snapshot.p_cols, snapshot.r_cols  # lint: unlocked-ok (parsed trace frame, not a live session)
        if arena_state is not None:
            am = meta.get("arena") or {}
            arena_state["warm_solves"] = int(am.get("warm_solves", 0))
            arena_state["dual_age"] = int(am.get("dual_age", 0))
            arena_state["weights_key"] = tuple(
                am.get("weights_key") or meta["weights"]
            )
            arena_state["native_isa"] = str(am.get("native_isa", "scalar"))
            arena.restore_state(
                tfmt._as_ns(p_cols), tfmt._as_ns(r_cols), arena_state
            )
        session = SolveSession(
            session_id=meta["session_id"],
            fingerprint=meta["fingerprint"],
            weights=CostWeights(*meta["weights"]),
            kernel=meta["kernel"],
            threads=threads,
            top_k=int(meta["top_k"]),
            p_cols=p_cols,
            r_cols=r_cols,
            n_providers=int(meta["n_providers"]),
            n_tasks=int(meta["n_tasks"]),
            arena=arena,
            tick=int(meta["tick"]),
            budget=budget,
            arena_bytes=estimate_arena_bytes(
                p_cols, r_cols, int(meta["top_k"])
            ),
        )
        stream_meta = meta.get("stream")
        if stream_meta and arena._p4t is not None:
            # re-arm the stream engine over the restored warm arena
            # with the FULL exported state (dedup cursors, cadence
            # cursor, counters — see StreamEngine.from_state); a carry
            # that degraded to cold (no arena state) stays a batch
            # session — the client's ladder re-opens with stream_mode,
            # an honest degrade rather than an unprimed engine
            from protocol_tpu.stream.engine import StreamEngine

            session.stream = StreamEngine.from_state(
                arena, CostWeights(*meta["weights"]), stream_meta
            )
        # fresh object, not yet visible to any store: no lock exists yet
        session.delta_rows_total = int(meta.get("delta_rows_total", 0))  # lint: unlocked-ok (fresh object)
        session.last_delta_crc = int(meta.get("last_delta_crc", 0))  # lint: unlocked-ok (fresh object)
        if outcome is not None:
            session.last_p4t = np.asarray(  # lint: unlocked-ok (fresh object)
                outcome.provider_for_task, np.int32
            )
        return session

    def load_one(self, session_id: str, budget=None):
        """Rehydrate ONE session from this process's namespace (the
        lazy-restore path behind a delta miss after a migration
        handoff). None = no journal here, or unloadable (warned — the
        client falls down the ladder). The ownership re-check after the
        read closes the rename race: a journal handed off mid-read is
        discarded, never served."""
        path = self.path_for(session_id)
        if not os.path.exists(path):
            return None
        try:
            session = self._load(path, budget)
        except Exception:
            self.journals_skipped += 1
            log.warning(
                "skipping unloadable session checkpoint %s", path,
                exc_info=True,
            )
            return None
        if session.session_id != session_id:
            # hash-prefix collision between two session ids: refuse
            # rather than serve someone else's state
            return None
        if not os.path.exists(path):
            # handed off to another namespace while we were reading:
            # the target owns it now
            return None
        return session

    def drop(self, session_id: str) -> None:
        """Remove a session's checkpoint (explicit client drop — an
        evicted-for-pressure session keeps its file: resurrecting it on
        restart is harmless, a same-id reopen just overwrites)."""
        try:
            os.remove(self.path_for(session_id))
        except OSError:
            pass


def handoff_orphans(
    root: str,
    src_proc_id: str,
    route,
    topology: Optional[dict] = None,
    stats: Optional[dict] = None,
) -> list:
    """Re-route a DEAD (or ejected) process's journal namespace: every
    loadable journal under ``<root>/<src_proc_id>/`` is renamed into
    the namespace ``route(session_id)`` picks (None = leave in place).
    Returns ``[(session_id, dst_proc_id), ...]`` for the journals
    moved. The source namespace's FENCE is superseded FIRST (stamped
    with ``topology``, the post-ejection ring): a paused-not-dead
    source that resumes mid- or post-handoff finds its fence
    superseded and refuses to flush or ack — re-routing is safe even
    when "dead" was really "wedged". A journal whose META frame is
    torn (process killed mid-flush) is SKIPPED with a counted
    ``journals_skipped`` warning instead of raising out of the
    re-route loop — the affected client re-opens down the ladder, the
    remaining journals still move. ``stats`` (optional dict) receives
    ``journals_moved`` / ``journals_skipped`` / ``fence_epoch``."""
    src_dir = os.path.join(root, str(src_proc_id))
    moved = []
    if stats is None:
        stats = {}
    stats.setdefault("journals_moved", 0)
    stats.setdefault("journals_skipped", 0)
    # fence FIRST, then enumerate: a wedged-but-running source that
    # flushes between the listing and the stamp would land a journal
    # that is neither moved nor fence-refused — stamping before the
    # listdir means any flush that beats the stamp is IN the listing,
    # and any flush after it is refused by the fence
    stats["fence_epoch"] = stamp_fence(
        root, src_proc_id, topology=topology
    )
    try:
        names = sorted(
            n for n in os.listdir(src_dir) if n.endswith(_SUFFIX)
        )
    except OSError:
        return moved
    for name in names:
        path = os.path.join(src_dir, name)
        sid = journal_session_id(path)
        if sid is None:
            stats["journals_skipped"] += 1
            log.warning(
                "orphan journal %s has no readable META "
                "(torn mid-flush?) — skipped, not fatal", path,
            )
            continue
        dst_proc = route(sid)
        if dst_proc is None or str(dst_proc) == str(src_proc_id):
            continue
        dst_dir = os.path.join(root, str(dst_proc))
        os.makedirs(dst_dir, exist_ok=True)
        try:
            os.replace(path, os.path.join(dst_dir, name))
        except OSError:
            stats["journals_skipped"] += 1
            log.warning("orphan handoff failed for %s", path,
                        exc_info=True)
            continue
        moved.append((sid, str(dst_proc)))
    stats["journals_moved"] = len(moved)
    return moved
