"""KV HTTP API: the shared state store as its own pod.

The reference's services scale because Redis is a separate process every
replica talks to (orchestrator api/processor modes share one Redis,
orchestrator/src/main.rs modes + store/core/redis.rs). This service is
that seam for the in-process KV engine: one kv-api pod owns the store
(optionally AOF-persisted) and any number of orchestrator replicas speak
``store.remote_kv.RemoteKVStore`` to it.

Surface: ``POST /kv/{op}`` with ``{"args": [...], "kwargs": {...}}``
for every KVStore method, plus an advisory lock
(``POST /kv/_lock`` acquire/release with token + TTL) that backs the
remote client's ``atomic()`` — cross-client read-modify-write sequences
serialize on it, mirroring how the reference leans on Redis pipelines /
SET NX for the same invariants.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

from aiohttp import web

from protocol_tpu.security.middleware import api_key_middleware
from protocol_tpu.store.kv import KVStore

# methods a remote client may invoke (everything stateful and public)
KV_OPS = {
    "set", "get", "mget", "incr", "delete", "exists", "expire", "ttl",
    "keys", "flushall", "hset", "hset_mapping", "hget", "hgetall", "hdel",
    "hincrby", "sadd", "srem", "smembers", "sismember", "scard", "zadd",
    "zscore", "zrem", "zrangebyscore", "zremrangebyscore", "zcard",
    "rpush", "lpush", "lrange", "lrem", "llen",
}


def _jsonable(value):
    if isinstance(value, set):
        return sorted(value)
    if isinstance(value, tuple):
        return list(value)
    if isinstance(value, list):
        return [_jsonable(v) for v in value]
    return value


class KvApiService:
    def __init__(
        self,
        kv: Optional[KVStore] = None,
        api_key: str = "admin",
        lock_ttl: float = 5.0,
    ):
        self.kv = kv or KVStore()
        self.api_key = api_key
        self.lock_ttl = lock_ttl
        self._lock_token: Optional[str] = None
        self._lock_expires = 0.0
        from prometheus_client import (
            CollectorRegistry,
            Counter,
            Histogram,
            generate_latest,
        )

        self._generate_latest = generate_latest
        self.registry = CollectorRegistry()
        self.op_requests = Counter(
            "kv_api_requests",
            "KV API requests by op and outcome",
            ["op", "outcome"],
            registry=self.registry,
        )
        self.op_duration = Histogram(
            "kv_api_op_duration_seconds",
            "KV op execution time",
            ["op"],
            buckets=[0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5],
            registry=self.registry,
        )

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[api_key_middleware(self.api_key, ["/kv"])]
        )
        app.router.add_post("/kv/_lock", self.lock_op)
        app.router.add_post("/kv/{op}", self.kv_op)
        app.router.add_get("/health", self.health)
        app.router.add_get("/metrics", self.metrics)
        return app

    async def metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            body=self._generate_latest(self.registry), content_type="text/plain"
        )

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    def _lock_live(self) -> bool:
        return (
            self._lock_token is not None and time.monotonic() < self._lock_expires
        )

    async def lock_op(self, request: web.Request) -> web.Response:
        body = await request.json()
        action = body.get("action")
        token = body.get("token", "")
        if action == "acquire":
            if self._lock_live() and token != self._lock_token:
                return web.json_response(
                    {"success": False, "error": "locked"}, status=423
                )
            self._lock_token = token or uuid.uuid4().hex
            self._lock_expires = time.monotonic() + self.lock_ttl
            return web.json_response(
                {"success": True, "data": self._lock_token}
            )
        if action == "release":
            if token == self._lock_token:
                self._lock_token = None
            return web.json_response({"success": True})
        return web.json_response(
            {"success": False, "error": "unknown action"}, status=400
        )

    async def kv_op(self, request: web.Request) -> web.Response:
        op = request.match_info["op"]
        if op == "_pipeline":
            return await self._pipeline(request)
        if op not in KV_OPS:
            return web.json_response(
                {"success": False, "error": f"unknown op {op}"}, status=404
            )
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"success": False, "error": "invalid json"}, status=400
            )
        args = body.get("args", [])
        kwargs = body.get("kwargs", {})
        holder = body.get("lock_token", "")
        if holder:
            lost = self._holder_check(holder)
            if lost is not None:
                self.op_requests.labels(op=op, outcome="lock_lost").inc()
                return lost
        elif (
            self._lock_live()
            and op not in ("get", "mget", "hget", "hgetall", "smembers",
                           "sismember", "scard", "zscore", "zrangebyscore",
                           "zcard", "lrange", "llen", "keys", "exists", "ttl")
        ):
            # a live foreign lock blocks WRITES from other clients; reads pass
            return web.json_response(
                {"success": False, "error": "locked"}, status=423
            )
        return self._execute(op, args, kwargs)

    def _holder_check(self, holder: str) -> Optional[web.Response]:
        """An op carrying a lock token either renews the live lock it
        matches or fails 410: a holder that paused past lock_ttl (its lock
        expired, possibly reacquired by another client) has already lost
        its atomic section's serialization — the distinct error lets it
        detect the loss and retry the WHOLE section instead of silently
        interleaving its remaining ops with foreign writes."""
        if self._lock_live() and holder == self._lock_token:
            # activity-based renewal: a long atomic section whose ops keep
            # flowing never silently loses its serialization guarantee
            self._lock_expires = time.monotonic() + self.lock_ttl
            return None
        return web.json_response(
            {"success": False, "error": "lock-lost"}, status=410
        )

    def _execute(self, op: str, args: list, kwargs: dict) -> web.Response:
        t0 = time.perf_counter()
        try:
            result = getattr(self.kv, op)(*args, **kwargs)
        except TypeError as e:
            self.op_requests.labels(op=op, outcome="bad_params").inc()
            return web.json_response(
                {"success": False, "error": f"bad params: {e}"}, status=400
            )
        self.op_duration.labels(op=op).observe(time.perf_counter() - t0)
        self.op_requests.labels(op=op, outcome="ok").inc()
        return web.json_response({"success": True, "data": _jsonable(result)})

    async def _pipeline(self, request: web.Request) -> web.Response:
        """Atomic op batch in one round trip (the Redis pipeline shape)."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"success": False, "error": "invalid json"}, status=400
            )
        ops = body.get("ops", [])
        try:
            ok = all(
                isinstance(entry, (list, tuple))
                and len(entry) == 3
                and entry[0] in KV_OPS
                and isinstance(entry[1], list)
                and isinstance(entry[2], dict)
                for entry in ops
            )
        except TypeError:
            ok = False
        if not isinstance(ops, list) or not ok:
            return web.json_response(
                {"success": False, "error": "bad pipeline entry"}, status=400
            )
        holder = body.get("lock_token", "")
        if holder:
            lost = self._holder_check(holder)
            if lost is not None:
                self.op_requests.labels(op="_pipeline", outcome="lock_lost").inc()
                return lost
        elif self._lock_live():
            return web.json_response(
                {"success": False, "error": "locked"}, status=423
            )
        t0 = time.perf_counter()
        try:
            results = self.kv.pipeline_execute(
                [(op, args, kwargs) for op, args, kwargs in ops]
            )
        except TypeError as e:
            self.op_requests.labels(op="_pipeline", outcome="bad_params").inc()
            return web.json_response(
                {"success": False, "error": f"bad params: {e}"}, status=400
            )
        self.op_duration.labels(op="_pipeline").observe(time.perf_counter() - t0)
        self.op_requests.labels(op="_pipeline", outcome="ok").inc()
        return web.json_response(
            {"success": True, "data": [_jsonable(r) for r in results]}
        )
