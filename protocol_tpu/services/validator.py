"""Validator: hardware attestation + synthetic-data work validation.

Reference: crates/validator (5,288 LoC; SURVEY.md §2.6, loop §3.6). Kept:

  - main loop: validate submitted work, fetch non-validated nodes from
    discovery, stake-gate providers (cached), run hardware challenges
    (validator/src/main.rs:434-631)
  - hardware challenge: random dense matmul round-trip, result comparison,
    then ledger validate_node (validators/hardware.rs:34-97,
    hardware_challenge.rs). The reference matmuls with nalgebra on CPU;
    here both sides compute with jnp on their accelerator.
  - toploc client: external verification service speaking
    POST /validate/{file} & /validategroup/{file},
    GET /status/{file} & /statusgroup/{file} ->
    {status, input_flops, output_flops, failing_indices, reason}; bearer
    auth; per-model file_prefix_filter routing
    (validators/synthetic_data/toploc.rs:83-397)
  - work-key lifecycle in the KV store: work_validation_status:{key},
    work_info:{key}, rejection zset; sha -> file resolution through the
    storage mapping; filename-regex grouping
    ``...-(groupid)-(size)-(filenum)-(idx).ext`` with completeness tracking
    and an incomplete-group grace window -> soft invalidation; hard
    invalidation (+penalty) for toploc rejections, soft for work-unit
    mismatches (validators/synthetic_data/mod.rs:119-1620, types.rs:49-169)
"""

from __future__ import annotations

import asyncio
import json
import re
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

import numpy as np
from aiohttp import web

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.models.node import DiscoveryNode
from protocol_tpu.security.signer import sign_request
from protocol_tpu.security.wallet import Wallet
from protocol_tpu.store.kv import KVStore
from protocol_tpu.utils.metrics import ValidatorMetrics

STATUS_KEY = "work_validation_status:{}"
WORK_INFO_KEY = "work_info:{}"
REJECTIONS_ZSET = "work_rejections"
GROUP_HASH = "group:{}:{}:{}"  # group_id, size, file_num
INCOMPLETE_GROUPS_ZSET = "incomplete_groups"

# filename grouping regex (types.rs:113-169)
GROUP_RE = re.compile(r"-([A-Za-z0-9]+)-(\d+)-(\d+)-(\d+)\.[A-Za-z0-9]+$")


class ValidationResult:
    UNKNOWN = "Unknown"
    PENDING = "Pending"
    ACCEPT = "Accept"
    REJECT = "Reject"
    CRASHED = "Crashed"
    WORK_MISMATCH = "WorkUnitsMismatch"


@dataclass
class GroupKey:
    group_id: str
    size: int
    file_num: int
    index: int

    @classmethod
    def parse(cls, file_name: str) -> Optional["GroupKey"]:
        m = GROUP_RE.search(file_name)
        if not m:
            return None
        return cls(m.group(1), int(m.group(2)), int(m.group(3)), int(m.group(4)))


class ToplocClient:
    """HTTP client for the external verification service
    (toploc.rs:96-397)."""

    def __init__(
        self,
        server_url: str,
        http,
        auth_token: Optional[str] = None,
        file_prefix_filter: Optional[str] = None,
        metrics=None,  # ValidatorMetrics (validator/src/metrics.rs api_*)
    ):
        self.server_url = server_url.rstrip("/")
        self.http = http
        self.auth_token = auth_token
        self.file_prefix_filter = file_prefix_filter
        self.metrics = metrics

    def _record_api(self, endpoint: str, status: str, seconds: float) -> None:
        if self.metrics is None:
            return
        base = self.metrics._base()
        self.metrics.api_requests.labels(
            **base, endpoint=endpoint, status=status
        ).inc()
        self.metrics.api_duration.labels(**base, endpoint=endpoint).observe(
            seconds
        )

    def accepts(self, file_name: str) -> bool:
        return not self.file_prefix_filter or file_name.startswith(
            self.file_prefix_filter
        )

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.auth_token}"} if self.auth_token else {}

    async def trigger(self, file_name: str, group: bool = False) -> bool:
        kind = "validategroup" if group else "validate"
        t0 = time.perf_counter()
        try:
            async with self.http.post(
                f"{self.server_url}/{kind}/{file_name}", headers=self._headers()
            ) as resp:
                self._record_api(kind, str(resp.status), time.perf_counter() - t0)
                return resp.status == 200
        except Exception:
            self._record_api(kind, "error", time.perf_counter() - t0)
            return False

    async def status(self, file_name: str, group: bool = False) -> Optional[dict]:
        kind = "statusgroup" if group else "status"
        t0 = time.perf_counter()
        try:
            async with self.http.get(
                f"{self.server_url}/{kind}/{file_name}", headers=self._headers()
            ) as resp:
                self._record_api(kind, str(resp.status), time.perf_counter() - t0)
                if resp.status != 200:
                    return None
                return await resp.json()
        except Exception:
            self._record_api(kind, "error", time.perf_counter() - t0)
            return None


class SyntheticDataValidator:
    """Work-key pipeline (validators/synthetic_data/mod.rs)."""

    def __init__(
        self,
        ledger: Ledger,
        pool_id: int,
        storage,  # StorageProvider: resolve_mapping_for_sha
        toploc_clients: list[ToplocClient],
        kv: Optional[KVStore] = None,
        penalty: int = 10,
        grace_period: float = 300.0,
        work_window: float = 3600.0,
        persist_path: Optional[str] = None,
        metrics=None,  # ValidatorMetrics
    ):
        self.ledger = ledger
        self.pool_id = pool_id
        self.storage = storage
        self.clients = toploc_clients
        self.kv = kv or KVStore(persist_path=persist_path)
        self.penalty = penalty
        self.grace_period = grace_period
        self.work_window = work_window
        self.metrics = metrics
        if metrics is not None:
            for c in toploc_clients:
                if c.metrics is None:
                    c.metrics = metrics

    def _client_for(self, file_name: str) -> Optional[ToplocClient]:
        for c in self.clients:
            if c.accepts(file_name):
                return c
        return None

    def get_status(self, work_key: str) -> str:
        return self.kv.get(STATUS_KEY.format(work_key)) or ValidationResult.UNKNOWN

    def _set_status(self, work_key: str, status: str) -> None:
        self.kv.set(STATUS_KEY.format(work_key), status)
        if status in (ValidationResult.REJECT, ValidationResult.WORK_MISMATCH):
            self.kv.zadd(REJECTIONS_ZSET, {work_key: time.time()})

    async def validate_work_once(self) -> dict:
        """One tick: discover new work keys, resolve + group, trigger
        validations, poll statuses, process expired groups."""
        stats = {"triggered": 0, "accepted": 0, "rejected": 0, "soft": 0}
        since = time.time() - self.work_window
        work_items = await asyncio.to_thread(
            self.ledger.get_work_since, self.pool_id, since
        )
        if self.metrics is not None:
            # only keys still awaiting processing: the backlog gauge must
            # drain to 0, not sit at the window's total forever
            pending = sum(
                1
                for w in work_items
                if self.get_status(w.work_key) == ValidationResult.UNKNOWN
            )
            self.metrics.work_keys_to_process.labels(
                **self.metrics._base()
            ).set(pending)
        for work in work_items:
            key = work.work_key
            if self.get_status(key) != ValidationResult.UNKNOWN:
                continue
            file_name = await self.storage.resolve_mapping_for_sha(key)
            if file_name is None:
                continue  # retried next tick until the mapping lands
            self.kv.set(
                WORK_INFO_KEY.format(key),
                json.dumps(
                    {"file": file_name, "node": work.node, "units": work.work_units}
                ),
            )
            gk = GroupKey.parse(file_name)
            if gk is None:
                client = self._client_for(file_name)
                if client and await client.trigger(file_name):
                    self._set_status(key, ValidationResult.PENDING)
                    stats["triggered"] += 1
            else:
                ghash = GROUP_HASH.format(gk.group_id, gk.size, gk.file_num)
                self.kv.hset(ghash, str(gk.index), key)
                members = self.kv.hgetall(ghash)
                self._set_status(key, ValidationResult.PENDING)
                if len(members) >= gk.size:
                    # complete group -> group validation trigger. Only leave
                    # the incomplete set once the trigger actually landed;
                    # a transient toploc outage must keep the group eligible
                    # for retry / grace-expiry instead of stranding members
                    # in Pending forever.
                    client = self._client_for(file_name)
                    if client and await client.trigger(file_name, group=True):
                        stats["triggered"] += 1
                        self.kv.zrem(INCOMPLETE_GROUPS_ZSET, ghash)
                    elif self.kv.zscore(INCOMPLETE_GROUPS_ZSET, ghash) is None:
                        self.kv.zadd(INCOMPLETE_GROUPS_ZSET, {ghash: time.time()})
                else:
                    if self.kv.zscore(INCOMPLETE_GROUPS_ZSET, ghash) is None:
                        self.kv.zadd(INCOMPLETE_GROUPS_ZSET, {ghash: time.time()})

        stats.update(await self.poll_statuses_once())
        stats["expired_groups"] = await self.process_groups_past_grace()
        return stats

    async def poll_statuses_once(self) -> dict:
        """Status polling -> accept / hard invalidate (failing indices) /
        soft invalidate on work-unit mismatch (mod.rs:1248-1356)."""
        out = {"accepted": 0, "rejected": 0, "soft": 0}
        counted_reject_groups: set[str] = set()
        for skey in self.kv.keys("work_validation_status:*"):
            work_key = skey.split(":", 1)[1]
            if self.kv.get(skey) != ValidationResult.PENDING:
                continue
            raw = self.kv.get(WORK_INFO_KEY.format(work_key))
            if not raw:
                continue
            info = json.loads(raw)
            file_name = info["file"]
            gk = GroupKey.parse(file_name)
            client = self._client_for(file_name)
            if client is None:
                continue
            status = await client.status(file_name, group=gk is not None)
            if not status:
                continue
            result = status.get("status")
            if result == "Accept":
                reported = status.get("output_flops")
                if gk is not None:
                    await asyncio.to_thread(self._accept_group, gk, reported, out)
                else:
                    claimed = info.get("units", 0)
                    if reported is not None and claimed and reported != claimed:
                        # work-unit mismatch -> soft invalidate (types.rs:49-62)
                        await asyncio.to_thread(self._soft_invalidate, work_key)
                        out["soft"] += 1
                    else:
                        self._set_status(work_key, ValidationResult.ACCEPT)
                        out["accepted"] += 1
            elif result == "Reject":
                # one count per GROUP per poll, not per still-pending member
                if (
                    gk is not None
                    and self.metrics is not None
                    and gk.group_id not in counted_reject_groups
                ):
                    counted_reject_groups.add(gk.group_id)
                    self.metrics.group_validations.labels(
                        **self.metrics._base(),
                        group_id=gk.group_id,
                        result="reject",
                    ).inc()
                failing = status.get("failing_indices")
                if gk is not None and failing is not None:
                    ghash = GROUP_HASH.format(gk.group_id, gk.size, gk.file_num)
                    members = self.kv.hgetall(ghash)
                    for idx_str, member_key in members.items():
                        if int(idx_str) in failing:
                            await asyncio.to_thread(self._hard_invalidate, member_key)
                            out["rejected"] += 1
                        elif self.get_status(member_key) == ValidationResult.PENDING:
                            self._set_status(member_key, ValidationResult.ACCEPT)
                            out["accepted"] += 1
                else:
                    await asyncio.to_thread(self._hard_invalidate, work_key)
                    out["rejected"] += 1
            elif result == "Crashed":
                self._set_status(work_key, ValidationResult.CRASHED)
        return out

    def _accept_group(self, gk: GroupKey, reported, out: dict) -> None:
        """Group acceptance with the work-units check (mod.rs:972-1095,
        1248-1356): sum ALL members' claimed units and compare to the
        group-level output_flops with +/-1 tolerance; on mismatch,
        soft-invalidate only the nodes whose claim deviates from
        output_flops/num_nodes by more than 1 — honest members whose
        individual claims are a fraction of the total are still accepted."""
        ghash = GROUP_HASH.format(gk.group_id, gk.size, gk.file_num)
        members = []  # (work_key, node, units)
        for _idx, mkey in sorted(self.kv.hgetall(ghash).items()):
            raw = self.kv.get(WORK_INFO_KEY.format(mkey))
            minfo = json.loads(raw) if raw else {}
            members.append((mkey, minfo.get("node"), minfo.get("units", 0)))

        # per-node units map, reference overwrite semantics (mod.rs:972-988)
        node_units = {node: units for _k, node, units in members if node is not None}
        total = sum(units for _k, _n, units in members)
        mismatch = reported is not None and abs(total - reported) > 1
        if self.metrics is not None:
            self.metrics.group_work_units_check_total.labels(
                **self.metrics._base(),
                group_id=gk.group_id,
                result="mismatch" if mismatch else "match",
            ).inc()
            self.metrics.group_validations.labels(
                **self.metrics._base(), group_id=gk.group_id, result="accept"
            ).inc()
        bad_nodes = set()
        if mismatch and node_units:
            expected = reported // len(node_units)
            bad_nodes = {
                node
                for node, units in node_units.items()
                if abs(units - expected) > 1
            }
        for mkey, node, _units in members:
            if self.get_status(mkey) != ValidationResult.PENDING:
                continue
            if node in bad_nodes:
                self._soft_invalidate(mkey, group_key=ghash)
                out["soft"] += 1
            else:
                self._set_status(mkey, ValidationResult.ACCEPT)
                out["accepted"] += 1

    async def process_groups_past_grace(self) -> int:
        """Incomplete groups past the grace window -> soft-invalidate their
        members (mod.rs:119-308, 1528-1620)."""
        expired = self.kv.zrangebyscore(
            INCOMPLETE_GROUPS_ZSET, 0, time.time() - self.grace_period
        )
        count = 0
        for ghash, _ in expired:
            for member_key in self.kv.hgetall(ghash).values():
                if self.get_status(member_key) == ValidationResult.PENDING:
                    await asyncio.to_thread(self._soft_invalidate, member_key)
                    count += 1
            self.kv.zrem(INCOMPLETE_GROUPS_ZSET, ghash)
        return count

    def _hard_invalidate(self, work_key: str) -> None:
        try:
            self.ledger.invalidate_work(self.pool_id, work_key, penalty=self.penalty)
        except LedgerError:
            pass
        self._set_status(work_key, ValidationResult.REJECT)
        if self.metrics is not None:
            self.metrics.work_keys_invalidated.labels(**self.metrics._base()).inc()

    def _soft_invalidate(self, work_key: str, group_key: str = "") -> None:
        try:
            self.ledger.soft_invalidate_work(self.pool_id, work_key)
        except LedgerError:
            pass
        self._set_status(work_key, ValidationResult.WORK_MISMATCH)
        if self.metrics is not None:
            self.metrics.work_keys_soft_invalidated.labels(
                **self.metrics._base(), group_key=group_key
            ).inc()

    def rejections(self) -> list[tuple[str, float]]:
        return self.kv.zrangebyscore(REJECTIONS_ZSET)


DiscoveryFetcher = Callable[[], Awaitable[list[DiscoveryNode]]]


class ValidatorService:
    def __init__(
        self,
        wallet: Wallet,
        ledger: Ledger,
        pool_id: int,
        synthetic: Optional[SyntheticDataValidator] = None,
        discovery_fetcher: Optional[DiscoveryFetcher] = None,
        http=None,
        challenge_size: int = 64,
        challenge_tolerance: float = 1e-2,
    ):
        self.wallet = wallet
        self.ledger = ledger
        self.pool_id = pool_id
        self.synthetic = synthetic
        self.discovery_fetcher = discovery_fetcher
        self.http = http
        self.challenge_size = challenge_size
        self.challenge_tolerance = challenge_tolerance
        self._stake_cache: dict[str, tuple[bool, float]] = {}
        self.last_loop = 0.0
        self.rng = np.random.default_rng(0)
        self.metrics = ValidatorMetrics(wallet.address, pool_id)
        if synthetic is not None and synthetic.metrics is None:
            synthetic.metrics = self.metrics
            for c in synthetic.clients:
                if c.metrics is None:
                    c.metrics = self.metrics

    # ----- hardware validation (validators/hardware.rs) -----

    async def challenge_node(self, control_url: str) -> bool:
        """Matmul round-trip: both sides compute on their accelerator; the
        worker's answer must match within tolerance.

        Inputs travel as FixedF64 (utils/fixedf64.py) — the same
        DETERMINISM PROPERTY as the reference's FixedF64
        (hardware_challenge.rs:8-54) but a deliberately DIFFERENT wire:
        Q31.32 integers under ``matrix_*_fixed`` keys, where the
        reference ships 12-decimal strings in a ``data_a``/``rows_a``
        schema — the two wires are not mutually parseable (see
        PARITY.md). Either way both sides hold bit-identical float64
        inputs; the RESULT comparison stays tolerance-based because
        validator and worker legitimately run on different hardware."""
        from protocol_tpu.utils import fixedf64

        n = self.challenge_size
        a = self.rng.standard_normal((n, n), dtype=np.float32)
        b = self.rng.standard_normal((n, n), dtype=np.float32)
        # quantize locally FIRST so this side computes on exactly the
        # values the worker will decode
        a = fixedf64.roundtrip(a).astype(np.float32)
        b = fixedf64.roundtrip(b).astype(np.float32)
        # both wires during rollout: a pre-FixedF64 worker reads the float
        # lists (Python json round-trips them exactly), a current one
        # prefers the fixed ints
        payload = {
            "matrix_a_fixed": fixedf64.encode_array(a),
            "matrix_b_fixed": fixedf64.encode_array(b),
            "matrix_a": a.tolist(),
            "matrix_b": b.tolist(),
        }
        try:
            # digest-mode signing (security/signer.py) keeps the ~254 KB
            # matrix body under the EVM wallets' 64 KB keccak cap; the
            # guard stays because an oversized/unsignable body must fail
            # THIS challenge, never abort the whole validation tick
            headers, body = sign_request(
                "/control/challenge", self.wallet, payload
            )
        except ValueError:
            return False
        try:
            async with self.http.post(
                f"{control_url}/challenge", json=body, headers=headers
            ) as resp:
                if resp.status != 200:
                    return False
                data = await resp.json()
        except Exception:
            return False

        def compute():
            # device work off the event loop (synchronous jax call)
            import jax.numpy as jnp

            return np.asarray(jnp.asarray(a) @ jnp.asarray(b))

        expected = await asyncio.to_thread(compute)
        try:
            if "result_fixed" in data:
                got = fixedf64.decode_array(data["result_fixed"]).astype(
                    np.float32
                )
            else:
                got = np.asarray(data.get("result", []), dtype=np.float32)
        except (ValueError, TypeError):
            # worker-controlled payload: a malformed answer fails THIS
            # challenge, it must not abort the whole validation tick
            return False
        if got.shape != expected.shape:
            return False
        return bool(np.allclose(got, expected, atol=self.challenge_tolerance * n))

    def _stake_ok(self, provider: str) -> bool:
        """Stake gate with a per-provider cache (main.rs:561-613)."""
        cached = self._stake_cache.get(provider)
        if cached and time.time() - cached[1] < 300:
            return cached[0]
        ok = self.ledger.get_stake(provider) >= self.ledger.calculate_stake(
            self.ledger.get_provider_total_compute(provider)
        )
        self._stake_cache[provider] = (ok, time.time())
        return ok

    async def validation_loop_once(self) -> dict:
        """One main-loop tick (main.rs:434-631): work validation, then
        hardware validation of unvalidated nodes (sequential, as the
        reference requires for signer-nonce safety)."""
        self.last_loop = time.time()
        _t0 = time.perf_counter()
        stats: dict = {}
        if self.synthetic is not None:
            stats["work"] = await self.synthetic.validate_work_once()

        validated = 0
        if self.discovery_fetcher is not None:
            for dn in await self.discovery_fetcher():
                node_id = dn.node.id
                # ledger reads via to_thread: with a RemoteLedger these are
                # HTTP round-trips that must not pin the event loop
                if await asyncio.to_thread(self.ledger.is_node_validated, node_id):
                    continue
                if not await asyncio.to_thread(
                    self._stake_ok, dn.node.provider_address
                ):
                    continue
                urls = dn.node.worker_p2p_addresses or []
                if not urls:
                    continue
                if await self.challenge_node(urls[0]):
                    try:
                        await asyncio.to_thread(self.ledger.validate_node, node_id)
                        validated += 1
                    except LedgerError:
                        pass
        stats["validated_nodes"] = validated
        self.metrics.validation_loop_duration.labels(
            **self.metrics._base()
        ).observe(time.perf_counter() - _t0)
        return stats

    # ----- HTTP surface (main.rs:90-121, /rejections, /metrics) -----

    def make_app(self, stale_after: float = 120.0) -> web.Application:
        app = web.Application()

        async def health(request):
            if time.time() - self.last_loop > stale_after:
                return web.json_response({"status": "stale"}, status=503)
            return web.json_response({"status": "ok"})

        async def rejections(request):
            data = self.synthetic.rejections() if self.synthetic else []
            return web.json_response(
                {"success": True, "data": [{"key": k, "at": t} for k, t in data]}
            )

        async def metrics(request):
            n = len(self.synthetic.rejections()) if self.synthetic else 0
            extra = (
                "# TYPE validator_rejections_total gauge\n"
                f"validator_rejections_total {n}\n"
            )
            return web.Response(
                body=self.metrics.render() + extra.encode(),
                content_type="text/plain",
            )

        app.router.add_get("/health", health)
        app.router.add_get("/rejections", rejections)
        app.router.add_get("/metrics", metrics)
        return app
