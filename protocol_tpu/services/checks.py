"""Worker hardware/software checks: the admission substance.

Reference: crates/worker/src/checks/ (~1,100 LoC of host introspection).
On a real marketplace these checks are what stands between an operator's
claims and the specs the scheduler matches on:

  hardware/gpu.rs            NVML device enumeration + WORKER_VISIBLE_DEVICES
                             filtering -> here: nvidia-smi CSV parsing (no
                             NVML binding in this image; the binary is the
                             stable interface and a fake binary makes the
                             parser hermetically testable, same pattern as
                             the fake-docker runtime tests)
  hardware/storage*.rs       statvfs totals + mount-point scan for the
                             largest-usable data volume
  hardware/memory.rs         MemTotal/MemAvailable
  hardware/interconnect.rs   timed download/upload probe (pluggable URL;
                             zero-egress hosts record a warning, not a hang)
  software/docker.rs         docker installed / daemon up / NVIDIA runtime
  software/port.rs           bind-probe for the worker's advertise port

``run_all_checks`` composes them into (ComputeSpecs, IssueReport) — the
boot gate for ``cli.py check`` and the worker's serve path. Critical
issues block startup (checks/issue.rs gating via cli/command.rs:388-397);
warnings print and proceed.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import time
from dataclasses import dataclass
from typing import Optional

from protocol_tpu.models.node import GpuSpecs

# filesystems that can never be the data volume (storage_path.rs scan)
_PSEUDO_FS = {
    "proc", "sysfs", "devtmpfs", "devpts", "tmpfs", "cgroup", "cgroup2",
    "overlay", "squashfs", "autofs", "mqueue", "hugetlbfs", "debugfs",
    "tracefs", "securityfs", "pstore", "bpf", "binfmt_misc", "configfs",
    "fusectl", "ramfs", "rpc_pipefs", "nsfs",
}


@dataclass
class MountPoint:
    path: str
    fs_type: str
    total_gb: float
    available_gb: float


# ---------------------------------------------------------------- hardware


def detect_gpus(nvidia_smi: str = "nvidia-smi") -> list[GpuSpecs]:
    """GPU enumeration via the nvidia-smi CSV interface (gpu.rs:25-100).

    Honors WORKER_VISIBLE_DEVICES (comma-separated indices) exactly like
    the reference's NVML path. Devices are grouped by model into one
    GpuSpecs per distinct model (count + shared per-card memory + indices).
    Returns [] when no NVIDIA stack is present.
    """
    try:
        out = subprocess.run(
            [
                nvidia_smi,
                "--query-gpu=index,name,memory.total",
                "--format=csv,noheader,nounits",
            ],
            capture_output=True,
            text=True,
            timeout=20,
        )
    except (OSError, subprocess.TimeoutExpired):
        return []
    if out.returncode != 0:
        return []

    visible: Optional[set[int]] = None
    raw_visible = os.environ.get("WORKER_VISIBLE_DEVICES", "").strip()
    if raw_visible:
        try:
            visible = {int(x) for x in raw_visible.split(",") if x.strip()}
        except ValueError:
            visible = None

    by_model: dict[str, dict] = {}
    for line in out.stdout.splitlines():
        parts = [p.strip() for p in line.split(",")]
        if len(parts) < 3:
            continue
        try:
            idx = int(parts[0])
            mem_mb = int(float(parts[2]))
        except ValueError:
            continue
        if visible is not None and idx not in visible:
            continue
        model = parts[1].lower()
        slot = by_model.setdefault(
            model, {"indices": [], "memory_mb": mem_mb}
        )
        slot["indices"].append(idx)
    return [
        GpuSpecs(
            count=len(v["indices"]),
            model=model,
            memory_mb=v["memory_mb"],
            indices=sorted(v["indices"]),
        )
        for model, v in by_model.items()
    ]


def scan_mount_points(mounts_path: str = "/proc/mounts") -> list[MountPoint]:
    """Real (non-pseudo) mounted filesystems with capacity, largest
    available first (storage_path.rs mount scan)."""
    points: list[MountPoint] = []
    try:
        with open(mounts_path) as f:
            lines = f.readlines()
    except OSError:
        return points
    seen: set[str] = set()
    for line in lines:
        parts = line.split()
        if len(parts) < 3:
            continue
        _dev, path, fs_type = parts[0], parts[1], parts[2]
        if fs_type in _PSEUDO_FS or path in seen:
            continue
        seen.add(path)
        try:
            st = os.statvfs(path)
        except OSError:
            continue
        total = st.f_blocks * st.f_frsize / 1024**3
        avail = st.f_bavail * st.f_frsize / 1024**3
        if total <= 0:
            continue
        points.append(MountPoint(path, fs_type, total, avail))
    points.sort(key=lambda m: -m.available_gb)
    return points


def best_storage_path(
    mounts_path: str = "/proc/mounts", app_dir: str = "prime-worker"
) -> tuple[str, float]:
    """The mount with the most available space (the data volume the task
    runtime should use), as (app-dir path on it, available_gb). The root
    mount — and the fallback when /proc/mounts is unreadable — maps to
    /var/lib/<app_dir>, so callers always get a writable directory path."""
    points = scan_mount_points(mounts_path)
    if not points:
        return f"/var/lib/{app_dir}", shutil.disk_usage("/").free / 1024**3
    best = points[0]
    if best.path == "/":
        return f"/var/lib/{app_dir}", best.available_gb
    return os.path.join(best.path, app_dir), best.available_gb


def memory_check(meminfo_path: str = "/proc/meminfo") -> tuple[int, int]:
    """(MemTotal MB, MemAvailable MB); zeros when unreadable
    (memory.rs)."""
    total = avail = 0
    try:
        with open(meminfo_path) as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) // 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) // 1024
    except OSError:
        pass
    return total, avail


def interconnect_check(
    download_url: Optional[str] = None,
    upload_url: Optional[str] = None,
    http_get=None,
) -> Optional[float]:
    """Timed download probe -> Mbps (interconnect.rs:8-40). The reference
    hardcodes Cloudflare's speed endpoint; here the URL is injected (tests
    use a local server; zero-egress deployments leave it unset and the
    check records a warning instead of hanging)."""
    if download_url is None:
        return None
    try:
        if http_get is not None:
            t0 = time.perf_counter()
            data = http_get(download_url)
        else:
            import urllib.request

            t0 = time.perf_counter()
            with urllib.request.urlopen(download_url, timeout=30) as resp:
                data = resp.read()
        elapsed = max(time.perf_counter() - t0, 1e-9)
        return len(data) * 8.0 / (elapsed * 1e6)
    except Exception:
        return None


# ---------------------------------------------------------------- software


def check_docker(docker_bin: str = "docker") -> tuple[bool, bool, Optional[str]]:
    """(daemon_up, nvidia_runtime_present, error) via `docker info`
    (software/docker.rs:8-80). Uses the CLI like the container runtime
    does, so the fake-docker test pattern covers it."""
    if shutil.which(docker_bin) is None and not os.path.isabs(docker_bin):
        return False, False, f"{docker_bin} not installed"
    try:
        out = subprocess.run(
            [docker_bin, "info", "--format", "{{json .}}"],
            capture_output=True,
            text=True,
            timeout=20,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return False, False, str(e)
    if out.returncode != 0:
        return False, False, out.stderr.strip() or "docker daemon not running"
    nvidia = False
    try:
        info = json.loads(out.stdout)
        runtimes = info.get("Runtimes") or {}
        nvidia = any("nvidia" in r.lower() for r in runtimes)
    except (ValueError, AttributeError):
        pass
    return True, nvidia, None


def check_port_available(port: int, host: str = "0.0.0.0") -> Optional[str]:
    """Bind probe (software/port.rs:8-33); None = available."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.close()
        return None
    except OSError as e:
        return str(e)


# ---------------------------------------------------------------- composed


def run_all_checks(
    storage_path: str = "/",
    port: Optional[int] = None,
    nvidia_smi: str = "nvidia-smi",
    docker_bin: str = "docker",
    require_docker: bool = False,
    probe_accelerator: bool = True,
    speed_url: Optional[str] = None,
    mounts_path: str = "/proc/mounts",
):
    """The reference's full boot gate (cli/command.rs:361-397): hardware
    introspection + software checks -> (ComputeSpecs, IssueReport).

    GPU specs prefer real nvidia-smi enumeration over the JAX device probe
    (the probe proves an accelerator is reachable; the enumeration is what
    the marketplace matches on). Criticals gate startup; warnings print.
    """
    from protocol_tpu.services.worker import detect_compute_specs

    specs, report = detect_compute_specs(
        storage_path, probe_accelerator=probe_accelerator
    )

    gpus = detect_gpus(nvidia_smi)
    if gpus:
        # one GpuSpecs per model; the node advertises the largest pool
        primary = max(gpus, key=lambda g: g.count or 0)
        specs.gpu = primary
        if len(gpus) > 1:
            report.add(
                "warning",
                f"heterogeneous GPUs detected ({len(gpus)} models); "
                f"advertising {primary.model} x{primary.count}",
            )

    total_mb, avail_mb = memory_check()
    if total_mb and avail_mb < max(total_mb // 10, 1):
        report.add(
            "warning",
            f"only {avail_mb} MB of {total_mb} MB RAM available",
        )

    mounts = scan_mount_points(mounts_path)
    if mounts:
        best = mounts[0]
        if best.path not in ("/",) and best.available_gb > (
            shutil.disk_usage(storage_path).free / 1024**3
        ):
            report.add(
                "warning",
                f"larger data volume available at {best.path} "
                f"({best.available_gb:.0f} GB free); consider --storage-path",
            )

    if port is not None:
        err = check_port_available(port)
        if err is not None:
            report.add("critical", f"port {port} unavailable: {err}")

    daemon_up, nvidia_rt, docker_err = check_docker(docker_bin)
    if not daemon_up:
        report.add(
            "critical" if require_docker else "warning",
            f"docker: {docker_err}",
        )
    elif specs.gpu is not None and not nvidia_rt:
        report.add(
            "warning",
            "GPU present but docker has no NVIDIA runtime: GPU tasks will "
            "not see devices",
        )

    mbps = interconnect_check(speed_url)
    if speed_url is not None and mbps is None:
        report.add("warning", "interconnect speed probe failed")

    return specs, report
