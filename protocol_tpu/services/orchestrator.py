"""Orchestrator: pool coordination, health FSM, scheduling, storage.

Reference: crates/orchestrator (13,802 LoC; SURVEY.md §2.4). Surface kept:

  POST /heartbeat                worker-signed; ban check, task-state + p2p
                                 update, TTL'd beat, metric storage, reply
                                 carries the scheduled task
                                 (api/routes/heartbeat.rs:16-170)
  /tasks CRUD                    admin; name uniqueness; topology required
                                 when grouping is active (task.rs:46-80)
  /nodes, /nodes/{id}/ban        admin node views + ban
  /groups, /groups/configs       admin group views; force-regroup
  /metrics, /metrics/prometheus  pool metrics
  POST /storage/request-upload   worker-signed; 100 MB cap; per-address
                                 hourly rate limit; file-name template
                                 expansion with group vars + upload
                                 counters; mapping file + signed URL
                                 (api/routes/storage.rs:24-309)
  /health                        loop-watchdog gated
                                 (utils/loop_heartbeats.rs:77-137)

Loops (tickable, async-loop-wrapped in serve()):
  discovery_monitor_once   discovery sync + status reconciliation
                           (discovery/monitor.rs:90-420)
  invite_once              invite Discovered nodes with a ledger-verifiable
                           signed invite (node/invite.rs:73-223)
  status_update_once       heartbeat health FSM + dead-node ejection
                           (status_update/mod.rs:118-350)
  group management         via NodeGroupsPlugin.run_group_management()

The scheduling hot path is the TPU batch matcher
(protocol_tpu.sched.tpu_backend) behind the same get-task-for-node seam the
reference exposes (scheduler/mod.rs:26-74).
"""

from __future__ import annotations

import asyncio
import json
import logging
import posixpath
import re
import time
import uuid
from typing import Awaitable, Callable, Optional

from aiohttp import web

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.chain.ledger import invite_digest
from protocol_tpu.models.heartbeat import HeartbeatRequest
from protocol_tpu.models.metric import MetricEntry
from protocol_tpu.models.node import DiscoveryNode
from protocol_tpu.models.task import Task, TaskRequest
from protocol_tpu.sched import Scheduler
from protocol_tpu.sched.node_groups import NodeGroupsPlugin, UPLOAD_COUNTER_KEY
from protocol_tpu.security.middleware import (
    api_key_middleware,
    validate_signature_middleware,
)
from protocol_tpu.security.wallet import Wallet
from protocol_tpu.store.context import StoreContext
from protocol_tpu.store.remote_kv import LockLostError
from protocol_tpu.store.domains.node_store import NodeStatus, OrchestratorNode
from protocol_tpu.utils.metrics import OrchestratorMetrics
from protocol_tpu.utils.storage import StorageProvider

BAN_KEY = "orchestrator:banned:{}"
UPLOAD_RATE_KEY = "orchestrator:upload_rate:{}"
UPLOAD_SHA_OWNER_KEY = "orchestrator:upload_sha_owner:{}"

MAX_UPLOAD_BYTES = 100 * 1024 * 1024  # storage.rs:10
DEAD_MISS_THRESHOLD = 3  # status_update/mod.rs:43
WAITING_GIVE_UP_MISSES = 360  # status_update/mod.rs:295
LOOP_STALE_SECONDS = 120.0  # loop_heartbeats.rs

DiscoveryFetcher = Callable[[], Awaitable[list[DiscoveryNode]]]
InviteSender = Callable[[OrchestratorNode, dict], Awaitable[bool]]


def _parse_owner_claim(raw) -> Optional[dict]:
    """Owner-key value -> {"addr", "ts", "first"} ("ts" = last refresh,
    "first" = original claim time, for the total-age squat cap). Journals
    written before claims carried timestamps hold a bare address; treat
    those as epoch-old so they stay takeover-able exactly as they were."""
    if raw is None:
        return None
    try:
        rec = json.loads(raw)
        ts = float(rec["ts"])
        return {
            "addr": str(rec["addr"]),
            "ts": ts,
            "first": float(rec.get("first", ts)),
        }
    except (ValueError, TypeError, KeyError):
        return {"addr": str(raw), "ts": 0.0, "first": 0.0}


class OrchestratorService:
    def __init__(
        self,
        ledger: Ledger,
        pool_id: int,
        wallet: Wallet,  # the pool's compute-manager key
        store: Optional[StoreContext] = None,
        scheduler: Optional[Scheduler] = None,
        groups_plugin: Optional[NodeGroupsPlugin] = None,
        storage: Optional[StorageProvider] = None,
        discovery_fetcher: Optional[DiscoveryFetcher] = None,
        invite_sender: Optional[InviteSender] = None,
        admin_api_key: str = "admin",
        disable_ejection: bool = False,
        uploads_per_hour: int = 3,  # main.rs:76-78
        heartbeat_url: str = "http://localhost:8090",
        webhook=None,  # WebhookPlugin (plugins/webhook/mod.rs)
        control_http=None,  # aiohttp session for worker control-plane calls
        persist_path: Optional[str] = None,
        # signed-URL validity AND the takeover-refusal window: a claim may
        # be seized only once no URL issued for it can still be in flight.
        # Default matches the providers' 1 h expiry (100 MiB on a slow link
        # legitimately takes minutes; do not shrink this below worst-case
        # upload duration). Claims refreshed by own-sha re-requests are
        # still takeover-able after 4x this (total-age cap), so a live node
        # cannot squat a never-uploaded sha forever by re-requesting.
        upload_claim_grace: float = 3600.0,
        time_fn=time.time,
    ):
        self.ledger = ledger
        self.pool_id = pool_id
        self.wallet = wallet
        if store is None:
            # persist_path gives the coordinator the reference's
            # restart-survival property (Redis outliving the process,
            # store/core/redis.rs:38-72): nodes/tasks/groups/heartbeat
            # state journal to disk and reload on boot
            from protocol_tpu.store.kv import KVStore

            store = StoreContext(KVStore(persist_path=persist_path))
        self.store = store
        self.scheduler = scheduler or Scheduler(self.store)
        self.groups_plugin = groups_plugin
        self.storage = storage
        self.discovery_fetcher = discovery_fetcher
        self.invite_sender = invite_sender
        self.admin_api_key = admin_api_key
        self.disable_ejection = disable_ejection
        self.uploads_per_hour = uploads_per_hour
        self.heartbeat_url = heartbeat_url
        self.webhook = webhook
        self.control_http = control_http
        self.upload_claim_grace = upload_claim_grace
        self._time = time_fn
        self.loop_beats: dict[str, float] = {}
        self.metrics = OrchestratorMetrics(pool_id)
        self._observed_solve = 0  # last seen matcher solve seq
        if webhook is not None and groups_plugin is not None:
            groups_plugin.on_group_created = webhook.handle_group_created
            groups_plugin.on_group_dissolved = webhook.handle_group_destroyed

    async def _kv_section(self, fn, attempts: int = 3):
        """Run a KV atomic section off the event loop (each op is a
        blocking HTTP round trip on RemoteKVStore deployments). A section
        can lose its advisory lock mid-flight (kv-api restart, >lock_ttl
        stall); per the LockLostError contract the whole section — not the
        single op — is retried."""
        for attempt in range(attempts):
            try:
                return await asyncio.to_thread(fn)
            except LockLostError:
                if attempt == attempts - 1:
                    raise

    def _set_status(self, address: str, status: NodeStatus) -> None:
        """Status transition + webhook notification (the reference's
        StatusUpdatePlugin dispatch, plugins/mod.rs:17-34)."""
        node = self.store.node_store.get_node(address)
        old = node.status if node else None
        self.store.node_store.update_node_status(address, status)
        if self.webhook is not None and old is not None and old != status:
            self.webhook.handle_status_change(address, old.value, status.value)

    # ================= HTTP =================

    def make_app(self) -> web.Application:
        def _node_known_sync(address: str) -> bool:
            if self.store.kv.exists(BAN_KEY.format(address)):
                return False
            node = self.store.node_store.get_node(address)
            return node is not None and node.status not in (
                NodeStatus.EJECTED,
                NodeStatus.BANNED,
            )

        async def node_known(address: str) -> bool:
            # async validator: node exists and is not ejected/banned
            # (api/server.rs:170-185) — gates BOTH /heartbeat and /storage.
            # Store ops run in a thread: with a RemoteKVStore (api-mode
            # replicas) each is a blocking HTTP round-trip that must not
            # pin the event loop.
            return await asyncio.to_thread(_node_known_sync, address)

        app = web.Application(
            # raise aiohttp's 1 MiB default so the advertised 100 MB upload
            # cap is actually reachable (the handlers enforce it themselves)
            client_max_size=MAX_UPLOAD_BYTES + 65536,
            middlewares=[
                # NB: /storage/upload is NOT signature-gated — like a GCS
                # signed URL, its auth is the time-limited HMAC token bound
                # to the object name, issued by /storage/request-upload
                validate_signature_middleware(
                    self.store.kv,
                    ["/heartbeat", "/storage/request-upload"],
                    validator=node_known,
                ),
                api_key_middleware(
                    self.admin_api_key,
                    ["/tasks", "/nodes", "/groups", "/metrics", "/scheduler"],
                ),
            ]
        )
        app.router.add_post("/heartbeat", self.heartbeat)
        app.router.add_post("/storage/request-upload", self.request_upload)
        app.router.add_put("/storage/upload/{object_name:.+}", self.upload_object)
        app.router.add_post("/tasks", self.create_task)
        app.router.add_get("/tasks", self.list_tasks)
        app.router.add_delete("/tasks/{task_id}", self.delete_task)
        app.router.add_get("/nodes", self.list_nodes)
        app.router.add_post("/nodes/{address}/ban", self.ban_node)
        app.router.add_get("/nodes/{address}/logs", self.node_logs)
        app.router.add_post("/nodes/{address}/restart", self.node_restart)
        app.router.add_get("/groups/{group_id}/logs", self.group_logs)
        app.router.add_get("/groups", self.list_groups)
        app.router.add_get("/groups/configs", self.list_group_configs)
        app.router.add_post("/groups/force-regroup", self.force_regroup)
        app.router.add_get("/metrics", self.get_metrics)
        app.router.add_get("/metrics/prometheus", self.get_prometheus)
        app.router.add_get("/scheduler/stats", self.get_scheduler_stats)
        app.router.add_get("/health", self.health)
        app.router.add_get("/openapi.json", self.openapi)
        # interactive explorer over the spec (reference: Swagger UI at
        # api/server.rs:46-97; here a self-contained zero-egress page)
        from protocol_tpu.utils.api_docs import docs_handler

        app.router.add_get("/docs", docs_handler())
        return app

    async def openapi(self, request: web.Request) -> web.Response:
        """OpenAPI document generated from the live route table (the
        reference serves utoipa-generated Swagger, api/server.rs:46-97)."""
        paths: dict = {}
        for route in request.app.router.routes():
            if route.method in ("HEAD", "*") or route.resource is None:
                continue
            info = route.resource.get_info()
            path = info.get("path") or info.get("formatter")
            if not path or path in ("/openapi.json", "/docs"):
                continue
            doc = (route.handler.__doc__ or "").strip().splitlines()
            params = [
                {
                    "name": m.group(1),
                    "in": "path",
                    "required": True,
                    "schema": {"type": "string"},
                }
                for m in re.finditer(r"\{(\w+)(?::[^}]*)?\}", path)
            ]
            entry = {
                "summary": doc[0] if doc else "",
                "responses": {"200": {"description": "OK"}},
            }
            if params:
                entry["parameters"] = params
            paths.setdefault(re.sub(r"\{(\w+):[^}]*\}", r"{\1}", path), {})[
                route.method.lower()
            ] = entry
        return web.json_response(
            {
                "openapi": "3.0.3",
                "info": {
                    "title": "protocol_tpu orchestrator",
                    "version": "1.0",
                    "description": (
                        f"Pool {self.pool_id} coordination API "
                        "(heartbeats, tasks, nodes, groups, storage, metrics)"
                    ),
                },
                "paths": dict(sorted(paths.items())),
            }
        )

    async def health(self, request: web.Request) -> web.Response:
        now = time.monotonic()
        stale = {
            name: round(now - t, 1)
            for name, t in self.loop_beats.items()
            if now - t > LOOP_STALE_SECONDS
        }
        if stale:
            return web.json_response(
                {"status": "unhealthy", "stale_loops": stale}, status=503
            )
        return web.json_response({"status": "ok"})

    # ----- heartbeat (the hot path) -----

    def _heartbeat_store_ops(self, hb: HeartbeatRequest, address: str) -> bool:
        """Synchronous store section of the heartbeat; returns banned."""
        if self.store.kv.exists(BAN_KEY.format(address)):
            return True
        node = self.store.node_store.get_node(address)
        if node is not None:
            self.store.node_store.update_node_task(
                address, hb.task_id, hb.task_state_enum()
            )
            if hb.p2p_id and node.p2p_id != hb.p2p_id:
                self.store.node_store.update_node_p2p(
                    address, hb.p2p_id, hb.p2p_addresses
                )
            if hb.load is not None:
                # live load for the matcher's cost term. Clamp BEFORE the
                # comparison (a worker reporting >1.0 must not rewrite an
                # unchanged 1.0 every beat) and debounce at 0.01 — loadavg
                # jitters every beat and this is the heartbeat hot path
                clamped = min(max(float(hb.load), 0.0), 1.0)
                if abs((node.load or 0.0) - clamped) > 0.01:
                    node.load = clamped
                    self.store.node_store.update_node(node)
        self.store.heartbeat_store.beat(hb)
        if hb.metrics:
            entries = []
            for m in hb.metrics:
                try:
                    entries.append(MetricEntry.from_dict(m))
                except (KeyError, ValueError, TypeError):
                    continue
            if entries:
                self.store.metrics_store.store_metrics(entries, address)
        return False

    async def heartbeat(self, request: web.Request) -> web.Response:
        body = request.get("auth_body") or {}
        address = request["auth_address"]
        hb = HeartbeatRequest.from_dict(body)
        if hb.address.lower() != address:
            return _err("address mismatch", 401)

        # all store writes in one thread hop: with a RemoteKVStore these
        # are HTTP round-trips that must not pin the event loop
        banned = await asyncio.to_thread(self._heartbeat_store_ops, hb, address)
        if banned:
            return _err("node is banned", 401)

        self.metrics.record_heartbeat(address)
        # the batch solve runs device work; keep it off the event loop
        multi = getattr(self.scheduler, "get_tasks_for_node", None)
        if multi is not None:
            assigned = await asyncio.to_thread(multi, address)
        else:
            t = await asyncio.to_thread(self.scheduler.get_task_for_node, address)
            assigned = [t] if t is not None else []
        task = assigned[0] if assigned else None
        matcher = getattr(self.scheduler, "batch_matcher", None)
        if matcher is not None and matcher.last_solve_stats:
            stats = matcher.last_solve_stats
            seq = stats.get("seq", 0)
            if seq > self._observed_solve and "solve_ms" in stats:
                self._observed_solve = seq
                self.metrics.solve_duration.labels(
                    backend=type(matcher).__name__,
                    pool_id=str(self.pool_id),
                ).observe(stats["solve_ms"] / 1e3)
        data: dict = {"current_task": task.to_dict() if task else None}
        if len(assigned) > 1:
            # colocated node (ladder #5): several tasks share this
            # provider's capacity concurrently; multi-task-aware workers
            # run them all, legacy workers run current_task only
            data["assigned_tasks"] = [t.to_dict() for t in assigned]
        return web.json_response({"success": True, "data": data})

    # ----- storage (api/routes/storage.rs:24-309) -----

    async def request_upload(self, request: web.Request) -> web.Response:
        if self.storage is None:
            return _err("storage not configured", 501)
        body = request.get("auth_body") or {}
        address = request["auth_address"]

        try:
            file_name = str(body["file_name"])
            file_size = int(body["file_size"])
            sha256 = str(body["sha256"])
        except (KeyError, ValueError, TypeError):
            return _err("missing file_name/file_size/sha256", 400)
        # counted at ENTRY so the counter still moves when requests fail —
        # a flatlining upload counter during a storage outage would read as
        # "no traffic" exactly when the operator needs the opposite signal
        _mtask = (
            self.store.task_store.get_task(str(body.get("task_id")))
            if body.get("task_id")
            else None
        )
        self.metrics.record_upload_request(
            address, str(body.get("task_id") or ""), _mtask.name if _mtask else ""
        )
        # the sha becomes a storage object name (mapping/{sha}) and a KV key:
        # anything but plain LOWERCASE hex is rejected — mixed case would
        # alias one digest to multiple owner keys / mapping objects (a
        # case-variant sha could remap a victim's resolution), and honest
        # clients send hexdigest() output which is lowercase
        if not re.fullmatch(r"[0-9a-f]{64}", sha256):
            return _err("sha256 must be 64 lowercase hex chars", 400)
        task_id = body.get("task_id")

        if file_size > MAX_UPLOAD_BYTES:
            return _err("file too large", 400)

        # rate limit N/hour/address (storage.rs:80-104)
        rate_key = UPLOAD_RATE_KEY.format(address)
        count = self.store.kv.incr(rate_key)
        if count == 1:
            self.store.kv.expire(rate_key, 3600)
        if count > self.uploads_per_hour:
            return _err("upload rate exceeded", 429)

        object_name = file_name
        task = self.store.task_store.get_task(task_id) if task_id else None
        if task and task.storage_config and task.storage_config.file_name_template:
            object_name = self._expand_file_template(
                task.storage_config.file_name_template, file_name, address
            )

        # The reference leaves the object-name surface open; close it here:
        # a node must not write under mapping/ (the validator's sha ->
        # file-name resolution namespace) or it could misdirect validation
        # of a victim's pending work (hard invalidation + slash).
        norm = posixpath.normpath(object_name)
        if posixpath.isabs(norm) or norm == ".." or norm.startswith("../"):
            # provider-independent: escaping names must die here, not rely
            # on each StorageProvider's own path checks
            return _err("invalid object name", 400)
        if norm == "mapping" or norm.startswith("mapping/"):
            return _err("object name under mapping/ is reserved", 400)

        try:
            # URL first: an invalid object name must fail before any state
            # (sha ownership, mapping) is written. The URL's validity is
            # capped to the claim grace window: a claim may only be taken
            # over once NO signed URL issued for it can still be in flight
            url = await self.storage.generate_upload_signed_url(
                object_name,
                expires_in=self.upload_claim_grace,
                max_bytes=file_size,
            )
        except ValueError as e:  # e.g. path-escaping object names
            return _err(str(e), 400)

        # One sha, one owner: refuse re-mapping a sha another node already
        # claimed (prevents overwriting a victim's pending-work resolution).
        # Claimed only AFTER the object name validated; released if the
        # mapping write itself fails, so a failed request cannot squat a
        # victim's sha. The claim records a timestamp: between a legitimate
        # claimant's request-upload response and its signed-URL PUT neither
        # the mapping nor the object exists yet, so "object missing" alone
        # must not read as stale — takeover additionally requires the claim
        # to be older than the signed-URL expiry (upload_claim_grace).
        # KV ops run off the event loop (each is a blocking HTTP round trip
        # on RemoteKVStore deployments) and inside one atomic section so a
        # racing claimant cannot interleave with the read-modify-write.
        owner_key = UPLOAD_SHA_OWNER_KEY.format(sha256)

        def _claim_attempt():
            # lock-free fast path: set-nx is already atomic, and the common
            # case (fresh sha) must not serialize every upload on the
            # store-wide advisory lock
            now = self._time()
            mine = {"addr": address, "ts": now, "first": now}
            if self.store.kv.set(owner_key, json.dumps(mine), nx=True):
                return "claimed", mine
            with self.store.kv.atomic():
                now = self._time()
                mine = {"addr": address, "ts": now, "first": now}
                if self.store.kv.set(owner_key, json.dumps(mine), nx=True):
                    return "claimed", mine
                cur = _parse_owner_claim(self.store.kv.get(owner_key))
                if cur is None:  # released between set-nx and get: re-claim
                    self.store.kv.set(owner_key, json.dumps(mine))
                    return "claimed", mine
                if cur["addr"] == address:
                    # refresh the timestamp (this request issues a FRESH
                    # signed URL, so the takeover grace restarts — else a
                    # retried PUT could be seized mid-flight) but keep
                    # "first": the total-age cap below is what stops a
                    # live node from refresh-squatting a sha forever
                    mine = {"addr": address, "ts": now, "first": cur["first"]}
                    self.store.kv.set(owner_key, json.dumps(mine))
                    return "own", mine
                return "foreign", cur

        try:
            status, rec = await self._kv_section(_claim_attempt)
        except LockLostError:
            return _err("store contention, retry", 503)
        claimed_now = status == "claimed"
        if status == "foreign":
            # another node holds the claim — honored while it is live: only
            # if the mapped object never materialized (claimant crashed
            # before its PUT) AND the claim has outlived every signed URL
            # issued for it is it stale and takeover-able, so a dead node
            # cannot squat a deterministic artifact's sha forever while an
            # in-flight first upload cannot be seized mid-PUT. The total-age
            # cap bounds refresh-squatting: past 4x the grace with still no
            # object, the claim falls regardless of re-request refreshes.
            mapped = await self.storage.resolve_mapping_for_sha(sha256)
            uploaded = mapped is not None and await self.storage.file_exists(mapped)
            now = self._time()
            stale = (
                now - rec["ts"] >= self.upload_claim_grace
                or now - rec["first"] >= 4 * self.upload_claim_grace
            )
            if uploaded or not stale:
                return _err("sha256 already mapped by another node", 409)

            def _takeover():
                with self.store.kv.atomic():
                    latest = _parse_owner_claim(self.store.kv.get(owner_key))
                    if latest is not None and latest != rec:
                        return None  # a concurrent takeover moved first
                    t = self._time()
                    mine = {"addr": address, "ts": t, "first": t}
                    self.store.kv.set(owner_key, json.dumps(mine))
                    return mine

            try:
                rec = await self._kv_section(_takeover)
            except LockLostError:
                return _err("store contention, retry", 503)
            if rec is None:
                return _err("sha256 already mapped by another node", 409)
            claimed_now = True  # owns the claim now; release on failure below

        async def _release_if_mine():
            # only delete OUR record: an unconditional delete could drop a
            # successor's live claim if this request stalled past the grace
            # and lost a takeover race while its storage call was in flight.
            # Best-effort — a release lost to store trouble merely leaves a
            # claim that goes stale after the grace window
            def release():
                with self.store.kv.atomic():
                    latest = _parse_owner_claim(self.store.kv.get(owner_key))
                    if latest == rec:
                        self.store.kv.delete(owner_key)

            try:
                await self._kv_section(release)
            except Exception:
                logging.getLogger(__name__).warning(
                    "upload claim release failed for %s", owner_key, exc_info=True
                )

        try:
            await self.storage.generate_mapping_file(sha256, object_name)
        except ValueError as e:
            if claimed_now:
                await _release_if_mine()
            return _err(str(e), 400)
        except Exception:
            if claimed_now:
                await _release_if_mine()
            return _err("storage backend failure", 500)
        return web.json_response(
            {"success": True, "data": {"signed_url": url, "object_name": object_name}}
        )

    async def upload_object(self, request: web.Request) -> web.Response:
        """Signed-URL upload endpoint for the LocalDir provider (the dev
        stand-in for GCS's signed PUT)."""
        from protocol_tpu.utils.storage import LocalDirStorageProvider

        if not isinstance(self.storage, LocalDirStorageProvider):
            return _err("uploads not served by this deployment", 501)
        object_name = request.match_info["object_name"]
        try:
            expires = int(request.query.get("expires", "0"))
            max_bytes = int(request.query.get("max_bytes", "0"))
        except ValueError:
            return _err("invalid expires/max_bytes", 400)
        token = request.query.get("token", "")
        try:
            if not self.storage.verify_upload_url(
                object_name, expires, token, max_bytes=max_bytes
            ):
                return _err("invalid or expired upload token", 403)
        except ValueError:
            return _err("invalid object name", 400)
        # the HMAC binds the approved size; 0 means "global cap only"
        cap = min(max_bytes or MAX_UPLOAD_BYTES, MAX_UPLOAD_BYTES)
        if request.content_length and request.content_length > cap:
            return _err("file larger than approved size", 413)
        # stream to disk in chunks: concurrent 100 MB uploads must not
        # buffer whole bodies in orchestrator memory
        try:
            total = await self.storage.put_stream(
                object_name, request.content.iter_chunked(1 << 20), cap
            )
        except ValueError as e:  # size overflow or path-escaping name
            status = 413 if "approved size" in str(e) else 400
            return _err(str(e), status)
        return web.json_response({"success": True, "data": {"bytes": total}})

    def _expand_file_template(
        self, template: str, original_name: str, address: str
    ) -> str:
        """Template vars incl. group context + upload counters
        (storage.rs:127-215)."""
        group = None
        index = 0
        size = 0
        if self.groups_plugin is not None:
            group = self.groups_plugin.group_for_node(address)
            if group is not None:
                index = group.nodes.index(address) if address in group.nodes else 0
                size = len(group.nodes)
        counter_key = UPLOAD_COUNTER_KEY.format(
            address, group.id if group else "-", template
        )
        total_after = self.store.kv.incr(counter_key)
        out = template.replace("${ORIGINAL_NAME}", original_name)
        out = out.replace("${NODE_GROUP_ID}", group.id if group else "")
        out = out.replace("${NODE_GROUP_SIZE}", str(size))
        out = out.replace("${NODE_GROUP_INDEX}", str(index))
        out = out.replace("${TOTAL_UPLOAD_COUNT_AFTER}", str(total_after))
        out = out.replace("${CURRENT_FILE_INDEX}", str(max(0, total_after - 1)))
        return out

    # ----- tasks (api/routes/task.rs) -----

    async def create_task(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err("invalid json", 400)
        req = TaskRequest.from_dict(body)
        if not req.name or not req.image:
            return _err("name and image required", 400)
        if self.store.task_store.name_exists(req.name):
            return _err("task name already exists", 409)
        # topology requirement when grouping is active (task.rs:68-80).
        # Composed mode (groups plugin + batch matcher) relaxes it: plain
        # tasks are legal there — ungrouped nodes get them from the
        # individual batch solve while groups run topology tasks.
        if self.groups_plugin is not None:
            topos = (
                req.scheduling_config.allowed_topologies()
                if req.scheduling_config
                else []
            )
            composed = getattr(self.scheduler, "batch_matcher", None) is not None
            if not topos and not composed:
                return _err("task must declare allowed_topologies", 400)
            unknown = [
                t for t in topos if t not in self.groups_plugin.config_by_name
            ]
            if unknown:
                return _err(f"unknown topologies: {unknown}", 400)
        try:
            task = Task.from_request(req)
        except ValueError as e:
            return _err(str(e), 400)
        self.store.task_store.add_task(task)
        return web.json_response({"success": True, "data": task.to_dict()}, status=201)

    async def list_tasks(self, request: web.Request) -> web.Response:
        tasks = [t.to_dict() for t in self.store.task_store.get_all_tasks()]
        return web.json_response({"success": True, "data": tasks})

    async def delete_task(self, request: web.Request) -> web.Response:
        task = self.store.task_store.delete_task(request.match_info["task_id"])
        if task is None:
            return _err("task not found", 404)
        self.store.metrics_store.delete_metrics_for_task(task.id)
        return web.json_response({"success": True, "data": task.to_dict()})

    # ----- nodes -----

    async def list_nodes(self, request: web.Request) -> web.Response:
        status_filter = request.query.get("status")
        nodes = self.store.node_store.get_nodes()
        if status_filter:
            nodes = [n for n in nodes if n.status.value == status_filter]
        return web.json_response(
            {"success": True, "data": [n.to_dict() for n in nodes]}
        )

    async def ban_node(self, request: web.Request) -> web.Response:
        address = request.match_info["address"].lower()
        self.store.kv.set(BAN_KEY.format(address), "1")
        node = self.store.node_store.get_node(address)
        if node is not None:
            self._set_status(address, NodeStatus.BANNED)
            self.store.metrics_store.delete_metrics_for_node(address)
            if self.groups_plugin is not None:
                node.status = NodeStatus.BANNED
                self.groups_plugin.handle_status_change(node)
        return web.json_response({"success": True, "data": "banned"})

    # ----- node control proxies (reference: /nodes/{id}/logs|restart via
    # the p2p GetTaskLogs/Restart channels, api/routes/nodes.rs) -----

    async def _control_call(
        self, node: OrchestratorNode, method: str, path: str, timeout: float = 10.0
    ):
        """Signed control-plane call to a worker (the p2p channel analog).
        Non-2xx / success=false responses surface as errors — a rejected
        restart must not read as a successful one."""
        if self.control_http is None:
            return None, "control client not configured"
        url = (node.p2p_addresses or [None])[0]
        if not url:
            return None, "node has no control address"
        import aiohttp as _aiohttp

        from protocol_tpu.security.signer import sign_request

        req_timeout = _aiohttp.ClientTimeout(total=timeout)
        try:
            if method == "GET":
                headers, _ = sign_request(path, self.wallet)
                async with self.control_http.get(
                    f"{url}{path.removeprefix('/control')}",
                    headers=headers,
                    timeout=req_timeout,
                ) as resp:
                    data = await resp.json()
            else:
                headers, body = sign_request(path, self.wallet, {})
                async with self.control_http.post(
                    f"{url}{path.removeprefix('/control')}",
                    json=body,
                    headers=headers,
                    timeout=req_timeout,
                ) as resp:
                    data = await resp.json()
            if resp.status >= 400 or data.get("success") is False:
                return None, data.get("error", f"worker returned {resp.status}")
            return data, None
        except Exception as e:
            return None, str(e)

    async def node_logs(self, request: web.Request) -> web.Response:
        node = self.store.node_store.get_node(request.match_info["address"].lower())
        if node is None:
            return _err("node not found", 404)
        data, err = await self._control_call(node, "GET", "/control/logs")
        if err:
            return _err(err, 502)
        return web.json_response({"success": True, "data": data.get("logs", [])})

    async def node_restart(self, request: web.Request) -> web.Response:
        node = self.store.node_store.get_node(request.match_info["address"].lower())
        if node is None:
            return _err("node not found", 404)
        data, err = await self._control_call(node, "POST", "/control/restart")
        if err:
            return _err(err, 502)
        return web.json_response({"success": True})

    async def group_logs(self, request: web.Request) -> web.Response:
        """Per-member log fan-out (reference groups.rs:217-318)."""
        if self.groups_plugin is None:
            return _err("grouping not enabled", 400)
        group = self.groups_plugin.get_group(request.match_info["group_id"])
        if group is None:
            return _err("group not found", 404)
        async def fetch(addr: str):
            node = self.store.node_store.get_node(addr)
            if node is None:
                return addr, {"error": "unknown node"}
            data, err = await self._control_call(node, "GET", "/control/logs")
            return addr, ({"error": err} if err else data.get("logs", []))

        # concurrent fan-out with per-call timeouts: one wedged member must
        # not serialize/stall the whole group (groups.rs:217-318 fans out too)
        results = await asyncio.gather(*(fetch(a) for a in group.nodes))
        return web.json_response({"success": True, "data": dict(results)})

    # ----- groups -----

    async def list_groups(self, request: web.Request) -> web.Response:
        if self.groups_plugin is None:
            return web.json_response({"success": True, "data": []})
        groups = [g.to_dict() for g in self.groups_plugin.get_groups()]
        return web.json_response({"success": True, "data": groups})

    async def list_group_configs(self, request: web.Request) -> web.Response:
        if self.groups_plugin is None:
            return web.json_response({"success": True, "data": []})
        return web.json_response(
            {
                "success": True,
                "data": [c.to_dict() for c in self.groups_plugin.configurations],
            }
        )

    async def force_regroup(self, request: web.Request) -> web.Response:
        if self.groups_plugin is None:
            return _err("grouping not enabled", 400)
        stats = self.groups_plugin.run_group_management()
        return web.json_response({"success": True, "data": stats})

    # ----- metrics -----

    async def get_metrics(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"success": True, "data": self.store.metrics_store.get_all_metrics()}
        )

    async def get_scheduler_stats(self, request: web.Request) -> web.Response:
        """Admin view of the batch matcher's last-solve stats (kernel,
        warm usage, cache deltas, stall/truncation counters) — the
        observability handle soak runs and operators assert against."""
        matcher = getattr(self.scheduler, "batch_matcher", None)
        stats = dict(matcher.last_solve_stats) if matcher is not None else {}
        return web.json_response({"success": True, "data": stats})

    async def get_prometheus(self, request: web.Request) -> web.Response:
        """Prometheus exposition over the full metric-family registry
        (metrics/mod.rs:6-126); the store -> registry rebuild
        (metrics/sync_service.rs:37-180) runs at scrape time instead of on
        a 10 s loop."""
        self.metrics.sync(self.store, self.groups_plugin)
        return web.Response(
            body=self.metrics.render(), content_type="text/plain"
        )

    # ================= loops =================

    def _beat(self, loop_name: str) -> None:
        self.loop_beats[loop_name] = time.monotonic()

    INACTIVE_GRACE_SECONDS = 300.0  # monitor.rs:298-334 (5 min)

    async def discovery_monitor_once(self) -> int:
        """Sync nodes from discovery + reconcile statuses. Rule set mirrors
        discovery/monitor.rs:236-420, in its order:

        1. a non-Healthy node sharing its endpoint with a Healthy one -> Dead
        2. validated but provider no longer whitelisted -> Ejected
        3. Ejected + provider re-whitelisted -> Dead (so it can recover)
        4. inactive-on-ledger while Healthy, past a 5-min grace since the
           last status change -> Ejected (or Dead when still whitelisted)
        5. IP changes and missing locations are absorbed
        6. Dead + newer discovery update -> Discovered (+ spec refresh)
        7. zero balance -> LowBalance
        8. unknown nodes are added as Discovered unless their endpoint is
           already taken by a Healthy node
        """
        if self.discovery_fetcher is None:
            return 0
        discovered = await self.discovery_fetcher()
        seen: dict[str, DiscoveryNode] = {}
        for dn in discovered:  # dedup by id (monitor.rs:202-215)
            seen.setdefault(dn.node.id.lower(), dn)

        # one store read per tick, not per node; the healthy-endpoint index
        # is maintained incrementally for the only in-loop mutation that can
        # affect it (a HEALTHY node leaving HEALTHY)
        known = {n.address: n for n in self.store.node_store.get_nodes()}
        healthy_endpoints: dict[tuple[str, int], set[str]] = {}
        for o in known.values():
            if o.status == NodeStatus.HEALTHY:
                healthy_endpoints.setdefault((o.ip_address, o.port), set()).add(
                    o.address
                )

        def demote_healthy(address: str, status: NodeStatus) -> None:
            n = known.get(address)
            if n is not None and n.status == NodeStatus.HEALTHY:
                healthy_endpoints.get((n.ip_address, n.port), set()).discard(address)
            self._set_status(address, status)
            if n is not None:
                n.status = status
                n.last_status_change = time.time()

        changed = 0
        for addr, dn in seen.items():
            node = known.get(addr)
            owners = healthy_endpoints.get(
                (dn.node.ip_address, dn.node.port), set()
            )
            healthy_same_endpoint = len(owners - {addr})
            # start-of-iteration snapshot for rule 6 (monitor.rs:359-383
            # evaluates against the pre-tick node state, so a node marked
            # Dead earlier in this same tick can never be lifted here)
            orig_status = node.status if node else None
            orig_last_change = node.last_status_change if node else None

            if node is None:
                # rule 8: endpoint already owned by a healthy node -> skip
                if healthy_same_endpoint > 0:
                    continue
                fresh = OrchestratorNode(
                    address=addr,
                    ip_address=dn.node.ip_address,
                    port=dn.node.port,
                    status=NodeStatus.DISCOVERED,
                    compute_specs=dn.node.compute_specs,
                    p2p_id=dn.node.worker_p2p_id,
                    p2p_addresses=dn.node.worker_p2p_addresses,
                    location=dn.location,
                    price=dn.node.price,
                )
                self.store.node_store.add_node(fresh)
                known[addr] = fresh
                changed += 1
                continue

            # rule 1: endpoint squatting by a non-healthy node
            if healthy_same_endpoint > 0 and node.status != NodeStatus.HEALTHY:
                demote_healthy(addr, NodeStatus.DEAD)
                changed += 1
                continue

            # rule 2: whitelist revoked
            if dn.is_validated and not dn.is_provider_whitelisted:
                if node.status != NodeStatus.EJECTED:
                    demote_healthy(addr, NodeStatus.EJECTED)
                    changed += 1
            # rule 3: ejected + re-whitelisted -> dead (recoverable)
            if (
                dn.is_validated
                and dn.is_provider_whitelisted
                and node.status == NodeStatus.EJECTED
            ):
                demote_healthy(addr, NodeStatus.DEAD)
                changed += 1

            node = self.store.node_store.get_node(addr) or node
            known[addr] = node

            # rule 4: inactive on ledger while healthy, past the grace
            if not dn.is_active and node.status == NodeStatus.HEALTHY:
                past_grace = (
                    node.last_status_change is None
                    or time.time() - node.last_status_change
                    > self.INACTIVE_GRACE_SECONDS
                )
                if past_grace:
                    target = (
                        NodeStatus.DEAD
                        if dn.is_provider_whitelisted
                        else NodeStatus.EJECTED
                    )
                    demote_healthy(addr, target)
                    changed += 1
                    node = self.store.node_store.get_node(addr) or node
                    known[addr] = node

            # rule 5: absorb IP changes + missing locations (single write)
            dirty = False
            if node.ip_address != dn.node.ip_address:
                node.ip_address = dn.node.ip_address
                dirty = True
            if node.location is None and dn.location is not None:
                node.location = dn.location
                dirty = True
            # a LIVE cost-model input, not just a registration snapshot: a
            # provider re-registering with a new ask must reach the matcher
            # without dying first (rule 6 only covers Dead -> Discovered)
            if node.price != dn.node.price:
                node.price = dn.node.price
                dirty = True

            # rule 6: dead -> discovered on a newer discovery update, judged
            # against the START-of-tick snapshot: a node marked Dead earlier
            # in this very tick is not lifted (and, per the reference, both
            # timestamps must be present)
            if (
                orig_status == NodeStatus.DEAD
                and orig_last_change is not None
                and dn.last_updated
                and dn.last_updated > orig_last_change
            ):
                # spec refresh first, then the transition through _set_status
                # so webhook observers see Dead -> Discovered like every
                # other transition in this loop (monitor.rs:359-383)
                node.compute_specs = dn.node.compute_specs
                node.price = dn.node.price
                if dirty or node.compute_specs is not None:
                    self.store.node_store.update_node(node)
                    dirty = False
                self._set_status(addr, NodeStatus.DISCOVERED)
                node = self.store.node_store.get_node(addr) or node
                known[addr] = node
                changed += 1
            # rule 7: zero balance -> LowBalance
            elif dn.latest_balance == 0 and node.status == NodeStatus.HEALTHY:
                if dirty:
                    self.store.node_store.update_node(node)
                    dirty = False
                demote_healthy(addr, NodeStatus.LOW_BALANCE)
                changed += 1
            elif (
                node.status == NodeStatus.LOW_BALANCE
                and (dn.latest_balance or 0) > 0
            ):
                if dirty:
                    self.store.node_store.update_node(node)
                    dirty = False
                self._set_status(addr, NodeStatus.UNHEALTHY)
                changed += 1
            if dirty:
                self.store.node_store.update_node(node)
        return changed

    async def invite_once(self) -> int:
        """Invite Discovered nodes (node/invite.rs:73-223): build a signed,
        ledger-verifiable invite and deliver it via the pluggable sender
        (the reference's libp2p Invite protocol)."""
        if self.invite_sender is None:
            return 0
        invited = 0
        # possibly-remote ledger read off the event loop
        pool = await asyncio.to_thread(self.ledger.get_pool_info, self.pool_id)
        for node in self.store.node_store.get_uninvited_nodes():
            nonce = uuid.uuid4().hex
            expiration = time.time() + 600
            digest = invite_digest(
                pool.domain_id, self.pool_id, node.address, nonce, expiration
            )
            # NB: field name is invite_nonce — the request signer injects its
            # own replay "nonce" into every signed body, which must not
            # collide with the invite's ledger nonce
            payload = {
                "pool_id": self.pool_id,
                "domain_id": pool.domain_id,
                "invite_nonce": nonce,
                "expiration": expiration,
                "invite_signature": self.wallet.sign_message(digest),
                "heartbeat_url": self.heartbeat_url,
            }
            ok = await self.invite_sender(node, payload)
            if ok:
                self._set_status(node.address, NodeStatus.WAITING_FOR_HEARTBEAT)
                self.store.heartbeat_store.clear_unhealthy_counter(node.address)
                invited += 1
        return invited

    async def status_update_once(self) -> None:
        """Health FSM (status_update/mod.rs:215-312) + chain sync
        (:118-142)."""
        _t0 = time.perf_counter()
        try:
            # await-free body + possibly-remote ledger calls: run in a
            # thread so a stalled ledger API cannot pin the event loop
            # (and /health with it)
            await asyncio.to_thread(self._status_update_once)
        finally:
            self.metrics.status_update_execution_time.labels(
                pool_id=str(self.pool_id)
            ).observe(time.perf_counter() - _t0)

    def _status_update_once(self) -> None:
        hs = self.store.heartbeat_store
        for node in self.store.node_store.get_nodes():
            addr = node.address
            if node.status in (NodeStatus.BANNED, NodeStatus.EJECTED):
                continue
            beat = hs.get_heartbeat(addr)
            if beat is not None:
                if node.status in (
                    NodeStatus.UNHEALTHY,
                    NodeStatus.WAITING_FOR_HEARTBEAT,
                    NodeStatus.DISCOVERED,
                    NodeStatus.DEAD,
                    NodeStatus.HEALTHY,
                ):
                    in_pool = self.ledger.is_node_in_pool(self.pool_id, addr)
                    target = NodeStatus.HEALTHY if in_pool else NodeStatus.UNHEALTHY
                    if node.status != target:
                        self._set_status(addr, target)
                        if target != NodeStatus.HEALTHY and self.groups_plugin:
                            node.status = target
                            self.groups_plugin.handle_status_change(node)
                    hs.clear_unhealthy_counter(addr)
            else:
                if node.status == NodeStatus.HEALTHY:
                    self._set_status(addr, NodeStatus.UNHEALTHY)
                    hs.increment_unhealthy_counter(addr)
                    if self.groups_plugin:
                        node.status = NodeStatus.UNHEALTHY
                        self.groups_plugin.handle_status_change(node)
                elif node.status == NodeStatus.UNHEALTHY:
                    misses = hs.increment_unhealthy_counter(addr)
                    if misses >= DEAD_MISS_THRESHOLD:
                        self._mark_dead(node)
                elif node.status == NodeStatus.WAITING_FOR_HEARTBEAT:
                    misses = hs.increment_unhealthy_counter(addr)
                    if misses >= WAITING_GIVE_UP_MISSES:
                        self._mark_dead(node)

        # dead + in-pool -> eject (status_update/mod.rs:118-142)
        if not self.disable_ejection:
            for node in self.store.node_store.get_nodes():
                if node.status == NodeStatus.DEAD and self.ledger.is_node_in_pool(
                    self.pool_id, node.address
                ):
                    try:
                        self.ledger.eject_node(
                            self.pool_id, node.address, self.wallet.address
                        )
                    except LedgerError:
                        pass

    def _mark_dead(self, node: OrchestratorNode) -> None:
        self._set_status(node.address, NodeStatus.DEAD)
        # dead nodes lose their metrics (status_update/mod.rs:314-350)
        self.store.metrics_store.delete_metrics_for_node(node.address)
        if self.groups_plugin is not None:
            node.status = NodeStatus.DEAD
            self.groups_plugin.handle_status_change(node)

    async def group_management_once(self) -> dict:
        if self.groups_plugin is None:
            return {}
        return self.groups_plugin.run_group_management()

    # ================= runner =================

    async def serve(
        self,
        host: str = "127.0.0.1",
        port: int = 8090,
        monitor_interval: float = 10.0,
        invite_interval: float = 10.0,
        status_interval: float = 15.0,
        group_interval: float = 10.0,
    ) -> web.AppRunner:
        """Start the HTTP server + background loops (intervals mirror the
        reference: discovery 10 s, invites 10 s, status 15 s, groups 10 s)."""
        app = self.make_app()
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        # callers MUST keep the returned task references alive (the loop
        # holds tasks weakly); serve() parks them on the app
        app["loops"] = self.start_loops(
            monitor_interval, invite_interval, status_interval, group_interval
        )
        return runner

    def start_loops(
        self,
        monitor_interval: float = 10.0,
        invite_interval: float = 10.0,
        status_interval: float = 15.0,
        group_interval: float = 10.0,
    ) -> list:
        """Start the four service loops (the reference's processor-mode
        work); returns the task objects — hold them, or GC stops the pool."""
        import logging

        log = logging.getLogger("protocol_tpu.orchestrator")

        async def loop(name, fn, interval):
            while True:
                try:
                    await fn()
                    # beat only on success so /health surfaces a loop that
                    # fails every tick (loop_heartbeats.rs semantics)
                    self._beat(name)
                except Exception:
                    log.exception("loop %s tick failed", name)
                await asyncio.sleep(interval)

        return [
            asyncio.create_task(
                loop("discovery_monitor", self.discovery_monitor_once, monitor_interval)
            ),
            asyncio.create_task(loop("inviter", self.invite_once, invite_interval)),
            asyncio.create_task(
                loop("status_updater", self.status_update_once, status_interval)
            ),
            asyncio.create_task(
                loop("group_manager", self.group_management_once, group_interval)
            ),
        ]


def _err(msg: str, status: int) -> web.Response:
    return web.json_response({"success": False, "error": msg}, status=status)
