"""Discovery service: the node registry.

Reference: crates/discovery (SURVEY.md §2.3). Surface kept:

  PUT  /api/nodes          worker-signed registration. x-address must equal
                           node.id (node.rs:32-35); nodes active in a pool
                           are immutable except p2p fixups (:39-91); per-IP
                           active-node cap (:93-127); ledger existence check
                           (:140-150); pool ComputeRequirements gate via
                           specs.meets() (:152-197).
  GET  /api/pool/{id}      pool-filtered, validated+active nodes (signed
                           readers: pool creator/manager).
  GET  /api/validator      non-validated nodes for the validator (signed).
  GET  /api/platform       all nodes, paginated (admin API key).
  /health

Loops (tickable): chain_sync_once — refresh balance / active / validated /
whitelist flags from the ledger, writing only on change (chainsync/
sync.rs:16,76-87,135-222); location enrichment via a pluggable resolver
(location_enrichment.rs).
"""

from __future__ import annotations

import time
from typing import Awaitable, Callable, Optional

from aiohttp import web

from protocol_tpu.chain import Ledger
from protocol_tpu.models.api import ApiResponse
from protocol_tpu.models.node import (
    ComputeRequirements,
    DiscoveryNode,
    Node,
    NodeLocation,
)
from protocol_tpu.security.middleware import (
    api_key_middleware,
    validate_signature_middleware,
)
from protocol_tpu.store.kv import KVStore
from protocol_tpu.utils.lockwitness import make_lock

NODE_KEY = "node:{}"
NODE_IDS = "node:ids"
IP_INDEX = "node:ip:{}"  # per-IP ACTIVE-node set: O(1) per-IP cap checks

LocationResolver = Callable[[str], Awaitable[Optional[NodeLocation]]]


class DiscoveryNodeStore:
    """Redis-schema node store (discovery/src/store/node_store.rs:78-158)."""

    def __init__(self, kv: KVStore):
        self.kv = kv

    def put(self, dn: DiscoveryNode) -> None:
        dn.last_updated = time.time()
        with self.kv.atomic():
            prev = self.get(dn.node.id)
            if prev is not None and prev.node.ip_address != dn.node.ip_address:
                self.kv.srem(IP_INDEX.format(prev.node.ip_address), dn.node.id)
            self.kv.set(NODE_KEY.format(dn.node.id), dn.to_json())
            self.kv.sadd(NODE_IDS, dn.node.id)
            # only pool-ACTIVE nodes count toward the per-IP cap (reference
            # count_active_nodes_by_ip, discovery node_store.rs:55-75):
            # chain_sync's active-state writes maintain the index, so dead
            # or stale registrations never consume the cap
            if dn.node.ip_address:
                if dn.is_active:
                    self.kv.sadd(IP_INDEX.format(dn.node.ip_address), dn.node.id)
                else:
                    self.kv.srem(IP_INDEX.format(dn.node.ip_address), dn.node.id)

    def count_for_ip(self, ip: str, exclude: str = "") -> int:
        """Active nodes on this IP, excluding ``exclude`` (the reference's
        effective_count when re-registering an already-active node)."""
        members = self.kv.smembers(IP_INDEX.format(ip))
        return len(members - {exclude})

    def get(self, node_id: str) -> Optional[DiscoveryNode]:
        raw = self.kv.get(NODE_KEY.format(node_id))
        return DiscoveryNode.from_json(raw) if raw else None

    def all(self) -> list[DiscoveryNode]:
        ids = sorted(self.kv.smembers(NODE_IDS))
        raws = self.kv.mget(NODE_KEY.format(i) for i in ids)
        nodes = [DiscoveryNode.from_json(r) for r in raws if r]
        nodes.sort(key=lambda d: d.last_updated or 0, reverse=True)
        return nodes


class DiscoveryService:
    def __init__(
        self,
        ledger: Ledger,
        pool_id: int,
        kv: Optional[KVStore] = None,
        max_nodes_per_ip: int = 5,
        admin_api_key: str = "admin",
        location_resolver: Optional[LocationResolver] = None,
        persist_path: Optional[str] = None,
    ):
        self.ledger = ledger
        self.pool_id = pool_id
        self.kv = kv or KVStore(persist_path=persist_path)
        self.store = DiscoveryNodeStore(self.kv)
        self.max_nodes_per_ip = max_nodes_per_ip
        self.admin_api_key = admin_api_key
        self.location_resolver = location_resolver
        # _register_node and chain_sync_once run in worker threads (their
        # ledger calls may be remote HTTP): this lock restores the
        # read-modify-write serialization the event loop used to provide
        self._write_lock = make_lock("discovery")

    # ---------------- HTTP surface ----------------

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[
                validate_signature_middleware(
                    self.kv, ["/api/nodes", "/api/pool", "/api/validator"]
                ),
                api_key_middleware(self.admin_api_key, ["/api/platform"]),
            ]
        )
        app.router.add_put("/api/nodes", self.register_node)
        app.router.add_get("/api/pool/{pool_id}", self.get_pool_nodes)
        app.router.add_get("/api/validator", self.get_unvalidated_nodes)
        app.router.add_get("/api/platform", self.get_all_nodes)
        app.router.add_get("/health", self.health)
        return app

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def register_node(self, request: web.Request) -> web.Response:
        body = request.get("auth_body") or {}
        address = request["auth_address"]
        # await-free gate logic with (possibly remote) ledger round-trips:
        # off the event loop so a stalled ledger API cannot pin /health
        import asyncio

        return await asyncio.to_thread(self._register_node, body, address)

    def _register_node(self, body: dict, address: str) -> web.Response:
        with self._write_lock:
            return self._register_node_locked(body, address)

    def _register_node_locked(self, body: dict, address: str) -> web.Response:
        node = Node.from_dict(body)

        # x-address must be the node being registered (node.rs:32-35)
        if node.id.lower() != address:
            return _err("address mismatch", 401)

        # ledger existence: the node must be registered on the substrate
        if not self.ledger.node_exists(node.id):
            return _err("node not registered on ledger", 400)

        existing = self.store.get(node.id)

        # nodes active in a pool are immutable except p2p/gpu-index fixups
        # (node.rs:39-91)
        if existing and existing.is_active:
            kept = existing.node
            kept.worker_p2p_id = node.worker_p2p_id or kept.worker_p2p_id
            kept.worker_p2p_addresses = (
                node.worker_p2p_addresses or kept.worker_p2p_addresses
            )
            existing.node = kept
            self.store.put(existing)
            return web.json_response(ApiResponse(True, "updated p2p only").to_dict())

        # per-IP active-node cap (node.rs:93-127) — O(1) via the IP index,
        # not a full-store scan (fleet onboarding must stay linear).
        # NB inherited scope (same as the reference): the cap gates
        # REGISTRATION only; nodes registered while inactive that later all
        # join the pool are not re-checked at activation time.
        if self.store.count_for_ip(node.ip_address, exclude=node.id) >= self.max_nodes_per_ip:
            return _err("too many nodes from this IP", 429)

        # pool ComputeRequirements gate (node.rs:152-197)
        pool = self.ledger.get_pool_info(self.pool_id)
        if pool.pool_data_uri:
            try:
                reqs = ComputeRequirements.parse(pool.pool_data_uri)
            except ValueError:
                reqs = None
            if reqs is not None:
                specs = node.compute_specs
                if specs is None or not specs.meets(reqs):
                    return _err("node does not meet pool compute requirements", 400)

        dn = existing or DiscoveryNode(node=node)
        dn.node = node
        if dn.created_at is None:
            dn.created_at = time.time()
        self.store.put(dn)
        return web.json_response(ApiResponse(True, "ok").to_dict())

    async def get_pool_nodes(self, request: web.Request) -> web.Response:
        import asyncio

        # signed readers only: orchestrator (compute manager) or creator
        pool = await asyncio.to_thread(
            self.ledger.get_pool_info, int(request.match_info["pool_id"])
        )
        addr = request["auth_address"]
        if addr not in (pool.creator, pool.compute_manager_key):
            return _err("not authorized for pool", 401)
        nodes = [
            d.to_dict()
            for d in self.store.all()
            if d.node.compute_pool_id == pool.pool_id and d.is_validated
        ]
        return web.json_response({"success": True, "data": nodes})

    async def get_unvalidated_nodes(self, request: web.Request) -> web.Response:
        nodes = [d.to_dict() for d in self.store.all() if not d.is_validated]
        return web.json_response({"success": True, "data": nodes})

    async def get_all_nodes(self, request: web.Request) -> web.Response:
        try:
            page = int(request.query.get("page", "0"))
            per_page = min(int(request.query.get("per_page", "50")), 200)
        except ValueError:
            return _err("invalid pagination", 400)
        nodes = self.store.all()
        chunk = nodes[page * per_page : (page + 1) * per_page]
        return web.json_response(
            {
                "success": True,
                "data": [d.to_dict() for d in chunk],
                "total": len(nodes),
                "page": page,
            }
        )

    # ---------------- loops ----------------

    def chain_sync_once(self) -> int:
        """One sync tick (chainsync/sync.rs:46-132): refresh ledger-derived
        flags per node, writing only on change. Returns changed count."""
        with self._write_lock:
            return self._chain_sync_once_locked()

    def _chain_sync_once_locked(self) -> int:
        changed = 0
        for dn in self.store.all():
            node_id = dn.node.id
            is_validated = self.ledger.is_node_validated(node_id)
            in_pool = self.ledger.is_node_in_pool(self.pool_id, node_id)
            balance = self.ledger.balance_of(dn.node.provider_address)
            whitelisted = self.ledger.is_provider_whitelisted(dn.node.provider_address)
            blacklisted = (
                node_id.lower() in self.ledger.get_pool_info(self.pool_id).blacklist
            )
            if (
                dn.is_validated != is_validated
                or dn.is_active != in_pool
                or dn.latest_balance != balance
                or dn.is_provider_whitelisted != whitelisted
                or dn.is_blacklisted != blacklisted
            ):
                dn.is_validated = is_validated
                dn.is_active = in_pool
                dn.latest_balance = balance
                dn.is_provider_whitelisted = whitelisted
                dn.is_blacklisted = blacklisted
                self.store.put(dn)
                changed += 1
        return changed

    async def enrich_locations_once(self) -> int:
        """Fill missing node locations via the pluggable resolver
        (location_enrichment.rs, 30 s loop in the reference)."""
        if self.location_resolver is None:
            return 0
        enriched = 0
        for dn in self.store.all():
            if dn.location is None and dn.node.ip_address:
                loc = await self.location_resolver(dn.node.ip_address)
                if loc is not None:
                    dn.location = loc
                    self.store.put(dn)
                    enriched += 1
        return enriched


def _err(msg: str, status: int) -> web.Response:
    return web.json_response({"success": False, "error": msg}, status=status)
