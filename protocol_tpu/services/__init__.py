"""Control-plane services: discovery, orchestrator, worker, validator.

Each service mirrors its reference crate's API surface and loops
(SURVEY.md §2.3-2.6) as an asyncio aiohttp application over the in-process
KV store, wallet-signed security layer, and ledger substrate. Services are
constructed as objects with ``make_app()`` (HTTP surface) and explicit
``*_once()`` loop bodies so tests can tick them deterministically — the
hermetic equivalent of the reference's tokio interval loops.
"""
