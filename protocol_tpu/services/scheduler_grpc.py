"""Scheduler gRPC backend service.

The seam from BASELINE.json's north star: a control plane (the reference's
Rust orchestrator, or this repo's Python one) calls ``Assign`` with columnar
provider/requirement batches; the backend builds the cost structure on the
accelerator and returns the matching. Columnar fixed-width payloads keep the
(de)serialization cost linear in P+T — no per-entity JSON on the hot path
(SURVEY.md §7 hard part #6).

Service stubs are hand-wired with grpc generic handlers (no protoc grpc
plugin needed); messages come from protocol_tpu.proto.scheduler_pb2.

Kernels: "greedy" (first-fit scan), "auction" (dense Bertsekas),
"sinkhorn" (entropic OT + rounding), "topk" (streaming candidates + sparse
frontier auction — the scale path).
"""

from __future__ import annotations

import time
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from protocol_tpu.ops.cost import CostWeights, cost_matrix
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.sched.tpu_backend import TpuBatchMatcher

SERVICE_NAME = "protocol_tpu.scheduler.v1.SchedulerBackend"


def _np(arr, dtype):
    return np.asarray(list(arr), dtype=dtype)


def providers_from_proto(msg: pb.ProviderBatch) -> EncodedProviders:
    n = len(msg.gpu_count)
    return EncodedProviders(
        gpu_count=_np(msg.gpu_count, np.int32),
        gpu_mem_mb=_np(msg.gpu_mem_mb, np.int32),
        gpu_model_id=_np(msg.gpu_model_id, np.int32),
        has_gpu=_np(msg.has_gpu, bool),
        has_cpu=_np(msg.has_cpu, bool),
        cpu_cores=_np(msg.cpu_cores, np.int32),
        ram_mb=_np(msg.ram_mb, np.int32),
        storage_gb=_np(msg.storage_gb, np.int32),
        lat=_np(msg.lat, np.float32),
        lon=_np(msg.lon, np.float32),
        has_location=_np(msg.has_location, bool),
        price=_np(msg.price, np.float32),
        load=_np(msg.load, np.float32),
        valid=np.ones(n, bool),
    )


def requirements_from_proto(msg: pb.RequirementBatch) -> EncodedRequirements:
    t = len(msg.cpu_cores)
    k = max(int(msg.max_gpu_options), 1)
    w = max(int(msg.model_words), 1)
    return EncodedRequirements(
        cpu_required=_np(msg.cpu_required, bool),
        cpu_cores=_np(msg.cpu_cores, np.int32),
        ram_mb=_np(msg.ram_mb, np.int32),
        storage_gb=_np(msg.storage_gb, np.int32),
        gpu_opt_valid=_np(msg.gpu_opt_valid, bool).reshape(t, k),
        gpu_count=_np(msg.gpu_count, np.int32).reshape(t, k),
        gpu_mem_min=_np(msg.gpu_mem_min, np.int32).reshape(t, k),
        gpu_mem_max=_np(msg.gpu_mem_max, np.int32).reshape(t, k),
        gpu_total_mem_min=_np(msg.gpu_total_mem_min, np.int32).reshape(t, k),
        gpu_total_mem_max=_np(msg.gpu_total_mem_max, np.int32).reshape(t, k),
        gpu_model_mask=_np(msg.gpu_model_mask, np.uint32).reshape(t, k, w),
        gpu_model_constrained=_np(msg.gpu_model_constrained, bool).reshape(t, k),
        lat=_np(msg.lat, np.float32),
        lon=_np(msg.lon, np.float32),
        has_location=_np(msg.has_location, bool),
        priority=_np(msg.priority, np.float32),
        valid=np.ones(t, bool),
    )


def _pad_pow2(enc, n_real: int):
    """Pad an encoded batch to the next pow2 bucket with valid=False rows:
    the wire carries only real rows (no valid mask), while bucketed shapes
    keep the backend's jit cache from recompiling per batch size."""
    import dataclasses

    if n_real <= 0:
        return enc
    target = 1 << (n_real - 1).bit_length()
    if target == n_real:
        return enc
    out = {}
    for f in dataclasses.fields(enc):
        a = np.asarray(getattr(enc, f.name))
        pad = [(0, target - n_real)] + [(0, 0)] * (a.ndim - 1)
        out[f.name] = np.pad(a, pad)
    out["valid"] = np.concatenate(
        [np.ones(n_real, bool), np.zeros(target - n_real, bool)]
    )
    return dataclasses.replace(enc, **out)


class SchedulerBackendServicer:
    def __init__(self):
        from protocol_tpu.sched.cand_cache import CandidateMemo

        self._cand_memo = CandidateMemo()
        # persistent warm arena for the "native-mt" kernel: steady-state
        # Assign repeats (the heartbeat loop's byte-identical or lightly
        # churned fleets) reuse the candidate structure + auction duals and
        # recompute only dirty rows — the native twin of _cand_memo's
        # delta-awareness, but incremental rather than exact-repeat-only.
        # One lock: serve() runs a thread pool, and the arena mutates its
        # carried state in place (concurrent solves would corrupt the warm
        # structure that every later solve builds on)
        self._native_arena = None
        import threading

        self._native_lock = threading.Lock()

    def Assign(self, request: pb.AssignRequest, context) -> pb.AssignResponse:
        t0 = time.perf_counter()
        ep = providers_from_proto(request.providers)
        er = requirements_from_proto(request.requirements)
        if request.HasField("weights"):
            # submessage presence is real in proto3: a set weights message
            # is used verbatim, so a legitimate 0.0 weight survives the wire
            weights = CostWeights(
                price=request.weights.price,
                load=request.weights.load,
                proximity=request.weights.proximity,
                priority=request.weights.priority,
            )
        else:
            weights = CostWeights()
        kernel = request.kernel or "auction"

        P = int(np.asarray(ep.gpu_count).shape[0])
        T = int(np.asarray(er.cpu_cores).shape[0])
        if P == 0 or T == 0:
            # degenerate batches are legal: nothing to match
            return pb.AssignResponse(
                provider_for_task=[-1] * T,
                task_for_provider=[-1] * P,
                num_assigned=0,
                solve_ms=(time.perf_counter() - t0) * 1e3,
            )
        # bucket the batch (valid=False padding rows) so repeat calls reuse
        # the jit cache; replies are sliced back to the real row counts, and
        # padding rows are infeasible by mask so they never win assignments
        ep = _pad_pow2(ep, P)
        er = _pad_pow2(er, T)

        if kernel == "best":
            # per-provider argmin over compatible tasks: the one-to-many
            # unbounded phase of the batch matcher (many providers may pick
            # the same task, so this is not a matching kernel)
            from protocol_tpu.sched.tpu_backend import _solve_unbounded

            best, _feas = _solve_unbounded(ep, er, weights)
            t4p = np.asarray(best)[:P]
            return pb.AssignResponse(
                provider_for_task=[-1] * T,
                task_for_provider=t4p.tolist(),
                num_assigned=int((t4p >= 0).sum()),
                solve_ms=(time.perf_counter() - t0) * 1e3,
            )

        if kernel == "native" or kernel.startswith("native-mt"):
            # the C++ CPU engine behind the seam: "native" is the
            # single-threaded Gauss-Seidel solve, "native-mt[:N]" the
            # multi-threaded engine through the servicer's persistent warm
            # arena (N threads; absent/0 = all hardware threads — the
            # suffix spelling keeps the wire message unchanged)
            from protocol_tpu import native as native_mod

            P_real, T_real = P, T
            p_padded = int(np.asarray(ep.gpu_count).shape[0])
            if kernel == "native":
                cand_p, cand_c = native_mod.fused_topk_candidates(
                    ep, er, weights,
                    k=min(max(int(request.top_k) or 64, 1), p_padded),
                )
                p4t_full = native_mod.auction_sparse(
                    cand_p, cand_c, num_providers=p_padded
                )
                price_full = np.zeros(p_padded, np.float32)
            else:
                _, _, suffix = kernel.partition(":")
                try:
                    threads = int(suffix) if suffix else 0
                except ValueError:
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"bad native-mt thread suffix {kernel!r}",
                    )
                requested_k = max(int(request.top_k) or 64, 1)
                with self._native_lock:
                    if (
                        self._native_arena is None
                        or self._native_arena.k != requested_k
                    ):
                        # a changed k changes the whole candidate
                        # structure: a fresh arena (cold solve) is the
                        # only honest answer
                        from protocol_tpu.native.arena import (
                            NativeSolveArena,
                        )

                        self._native_arena = NativeSolveArena(
                            k=requested_k, threads=threads
                        )
                    self._native_arena.threads = threads
                    p4t_full = self._native_arena.solve(ep, er, weights)
                    price_full = self._native_arena.price
            p4t = np.asarray(p4t_full)[:T_real]
            t4p = np.full(P_real, -1, np.int32)
            for s_idx, p_idx in enumerate(p4t):
                if 0 <= p_idx < P_real:
                    t4p[p_idx] = s_idx
            return pb.AssignResponse(
                provider_for_task=p4t.tolist(),
                task_for_provider=t4p.tolist(),
                num_assigned=int((p4t >= 0).sum()),
                solve_ms=(time.perf_counter() - t0) * 1e3,
                price=np.asarray(price_full)[:P_real].tolist(),
            )

        if kernel == "topk":
            from protocol_tpu.ops.sparse import (
                assign_auction_sparse_scaled,
                assign_auction_sparse_warm,
            )

            # tile must divide the (padded, pow2) T
            t_padded = int(np.asarray(er.cpu_cores).shape[0])
            tile = min(1024, t_padded)
            while t_padded % tile != 0:
                tile -= 1
            p_padded = int(np.asarray(ep.gpu_count).shape[0])
            # bidirectional: same coverage-safe generator as the in-process
            # matcher (_bounded_t4p_sparse) — remote/in-process parity.
            # Content-hash memoized: the steady-state heartbeat loop sends
            # a byte-identical fleet, and the stateless seam must not
            # re-pay the O(P*T) generation for it (VERDICT r4 item 3)
            cand_p, cand_c = self._cand_memo.get(
                ep, er, weights,
                k=max(int(request.top_k) or 64, 1), tile=tile,
                reverse_r=8, extra=16,
            )
            if len(request.warm_price) == P and len(
                request.seed_provider_for_task
            ) == T:
                # stateless incremental solve: warm state rode the wire.
                # Wire input is untrusted: clamp out-of-range seeds and
                # drop duplicates (the warm kernel requires injectivity
                # over >= 0 — a duplicated provider index would produce a
                # corrupt two-tasks-one-provider "matching").
                price0 = np.zeros(p_padded, np.float32)
                price0[:P] = np.nan_to_num(
                    np.asarray(request.warm_price, np.float32),
                    nan=0.0, posinf=0.0, neginf=0.0,
                )
                p4t0 = np.full(t_padded, -1, np.int32)
                seeds = np.asarray(request.seed_provider_for_task, np.int32)
                seeds = np.where((seeds >= 0) & (seeds < P), seeds, -1)
                pos = seeds >= 0
                _, first = np.unique(seeds[pos], return_index=True)
                keep = np.zeros(int(pos.sum()), bool)
                keep[first] = True
                seeds[np.flatnonzero(pos)[~keep]] = -1
                p4t0[:T] = seeds
                res, price = assign_auction_sparse_warm(
                    cand_p, cand_c, p_padded,
                    price0=price0, p4t0=p4t0,
                    eps=request.eps or 0.02,
                    max_iters=int(request.max_iters) or 20000,
                )
            else:
                res, price = assign_auction_sparse_scaled(
                    cand_p, cand_c, p_padded,
                    eps_end=request.eps or 0.02,
                    max_iters_per_phase=int(request.max_iters) or 4000,
                    with_prices=True,
                )
            p4t = np.asarray(res.provider_for_task)[:T]
            t4p = np.asarray(res.task_for_provider)[:P]
            return pb.AssignResponse(
                provider_for_task=p4t.tolist(),
                task_for_provider=t4p.tolist(),
                num_assigned=int((p4t >= 0).sum()),
                solve_ms=(time.perf_counter() - t0) * 1e3,
                price=np.asarray(price)[:P].tolist(),
            )
        else:
            from protocol_tpu.ops.assign import (
                assign_auction,
                assign_greedy,
                assign_sinkhorn,
            )

            cost, _ = cost_matrix(ep, er, weights)
            if kernel == "greedy":
                res = assign_greedy(cost)
            elif kernel == "sinkhorn":
                res = assign_sinkhorn(
                    cost,
                    eps=request.eps or 0.05,
                    num_iters=int(request.max_iters) or 200,
                )
            elif kernel == "auction":
                from protocol_tpu.ops.cost import with_tie_jitter

                # same degeneracy breaker as the in-process dense solve
                # (sched/tpu_backend._solve_bounded) — identical jitter is
                # what RemoteBatchMatcher's parity with TpuBatchMatcher
                # rests on
                res = assign_auction(
                    with_tie_jitter(cost),
                    eps=request.eps or 0.01,
                    max_iters=int(request.max_iters) or 500,
                )
            else:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, f"unknown kernel {kernel!r}"
                )

        p4t = np.asarray(res.provider_for_task)[:T]
        t4p = np.asarray(res.task_for_provider)[:P]
        return pb.AssignResponse(
            provider_for_task=p4t.tolist(),
            task_for_provider=t4p.tolist(),
            num_assigned=int((p4t >= 0).sum()),
            solve_ms=(time.perf_counter() - t0) * 1e3,
        )

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        devices = jax.devices()
        return pb.HealthResponse(
            status="ok",
            platform=devices[0].platform if devices else "none",
            device_count=len(devices),
        )


def _handlers(servicer: SchedulerBackendServicer) -> grpc.GenericRpcHandler:
    return grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "Assign": grpc.unary_unary_rpc_method_handler(
                servicer.Assign,
                request_deserializer=pb.AssignRequest.FromString,
                response_serializer=pb.AssignResponse.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                servicer.Health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthResponse.SerializeToString,
            ),
        },
    )


# Columnar batches scale with the population: ~60 B/provider means the
# 4 MB gRPC default tops out near 70k providers. 1 GiB covers the 1M-scale
# ladder with headroom; it is a cap, not an allocation.
MAX_MESSAGE_BYTES = 1 << 30
_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]


def serve(address: str = "127.0.0.1:50061", max_workers: int = 4) -> grpc.Server:
    """Start the backend server (non-blocking; call .wait_for_termination())."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
    server.add_generic_rpc_handlers((_handlers(SchedulerBackendServicer()),))
    server.add_insecure_port(address)
    server.start()
    return server


class SchedulerBackendClient:
    """Thin client stub (what a non-Python control plane would generate)."""

    def __init__(self, address: str = "127.0.0.1:50061"):
        self.channel = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
        self._assign = self.channel.unary_unary(
            f"/{SERVICE_NAME}/Assign",
            request_serializer=pb.AssignRequest.SerializeToString,
            response_deserializer=pb.AssignResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE_NAME}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def assign(self, request: pb.AssignRequest, timeout: float = 60.0) -> pb.AssignResponse:
        return self._assign(request, timeout=timeout)

    def health(self, timeout: float = 10.0) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=timeout)

    def close(self) -> None:
        self.channel.close()


def encoded_to_proto(
    ep: EncodedProviders, er: EncodedRequirements, weights: Optional[CostWeights] = None,
    kernel: str = "topk", top_k: int = 64, eps: float = 0.01, max_iters: int = 0,
) -> pb.AssignRequest:
    """Host-side helper: pack numpy-backed encodings into an AssignRequest."""
    w = weights or CostWeights()
    t, k = np.asarray(er.gpu_opt_valid).shape
    words = np.asarray(er.gpu_model_mask).shape[-1]
    return pb.AssignRequest(
        providers=pb.ProviderBatch(
            gpu_count=np.asarray(ep.gpu_count).tolist(),
            gpu_mem_mb=np.asarray(ep.gpu_mem_mb).tolist(),
            gpu_model_id=np.asarray(ep.gpu_model_id).tolist(),
            has_gpu=np.asarray(ep.has_gpu).tolist(),
            has_cpu=np.asarray(ep.has_cpu).tolist(),
            cpu_cores=np.asarray(ep.cpu_cores).tolist(),
            ram_mb=np.asarray(ep.ram_mb).tolist(),
            storage_gb=np.asarray(ep.storage_gb).tolist(),
            lat=np.asarray(ep.lat).tolist(),
            lon=np.asarray(ep.lon).tolist(),
            has_location=np.asarray(ep.has_location).tolist(),
            price=np.asarray(ep.price).tolist(),
            load=np.asarray(ep.load).tolist(),
        ),
        requirements=pb.RequirementBatch(
            cpu_required=np.asarray(er.cpu_required).tolist(),
            cpu_cores=np.asarray(er.cpu_cores).tolist(),
            ram_mb=np.asarray(er.ram_mb).tolist(),
            storage_gb=np.asarray(er.storage_gb).tolist(),
            max_gpu_options=k,
            model_words=words,
            gpu_opt_valid=np.asarray(er.gpu_opt_valid).reshape(-1).tolist(),
            gpu_count=np.asarray(er.gpu_count).reshape(-1).tolist(),
            gpu_mem_min=np.asarray(er.gpu_mem_min).reshape(-1).tolist(),
            gpu_mem_max=np.asarray(er.gpu_mem_max).reshape(-1).tolist(),
            gpu_total_mem_min=np.asarray(er.gpu_total_mem_min).reshape(-1).tolist(),
            gpu_total_mem_max=np.asarray(er.gpu_total_mem_max).reshape(-1).tolist(),
            gpu_model_mask=np.asarray(er.gpu_model_mask).reshape(-1).tolist(),
            gpu_model_constrained=np.asarray(er.gpu_model_constrained).reshape(-1).tolist(),
            lat=np.asarray(er.lat).tolist(),
            lon=np.asarray(er.lon).tolist(),
            has_location=np.asarray(er.has_location).tolist(),
            priority=np.asarray(er.priority).tolist(),
        ),
        weights=pb.CostWeights(
            price=float(w.price), load=float(w.load),
            proximity=float(w.proximity), priority=float(w.priority),
        ),
        kernel=kernel,
        top_k=top_k,
        eps=eps,
        max_iters=max_iters,
    )


class RemoteBatchMatcher(TpuBatchMatcher):
    """TpuBatchMatcher whose device solves go through the gRPC scheduler
    backend (``scheduler_backend=remote``): the control plane stays a thin
    host process while the kernels run wherever the backend's accelerator
    lives. This is the load-bearing form of the BASELINE.json north-star
    seam — the same columnar batches the in-process matcher feeds its
    jitted kernels are packed into AssignRequests instead, so control
    plane and backend can be scaled and deployed independently (the
    reference's Rust-orchestrator-calls-TPU-service shape).

    Round-trip cost shows up in ``last_solve_stats`` as
    ``remote_rtt_ms`` (client-observed) next to the backend-reported
    ``solve_ms`` per call; the difference is the columnar seam's cost
    (SURVEY.md §7 hard part #6 wants it cheap — measured, not asserted).
    """

    # candidates are generated behind the seam; the in-process candidate
    # cache cannot hold them (warm prices still ride the wire)
    use_candidate_cache = False

    def attach_groups(self, plugin) -> None:
        # The group solve is tiny (groups x tasks) and runs in-process even
        # on the remote matcher — but this control-plane host must never
        # lazily initialize a remote accelerator platform (a wedged tunnel
        # would hang the solve path). Pin jax to the host CPU first; every
        # LARGE solve still rides the gRPC seam.
        import jax

        jax.config.update("jax_platforms", "cpu")
        super().attach_groups(plugin)

    def __init__(
        self,
        store,
        address: str = "127.0.0.1:50061",
        request_timeout: float = 300.0,
        **kwargs,
    ):
        super().__init__(store, **kwargs)
        self.request_timeout = request_timeout
        self.client = SchedulerBackendClient(address)
        self._rtt_ms: list[float] = []
        self._backend_ms: list[float] = []

    def refresh(self) -> None:
        self._rtt_ms, self._backend_ms = [], []
        super().refresh()  # replaces last_solve_stats; re-attach remote cost
        if self._rtt_ms:
            self.last_solve_stats["remote_calls"] = len(self._rtt_ms)
            self.last_solve_stats["remote_rtt_ms"] = round(sum(self._rtt_ms), 3)
            self.last_solve_stats["remote_backend_ms"] = round(
                sum(self._backend_ms), 3
            )

    @staticmethod
    def _strip_padding(enc):
        """Drop the pow2-padding rows before serialization: the wire format
        carries no valid mask, so padded rows would otherwise become real
        (zero-cost, always-compatible) entities on the backend — and they
        double the payload for nothing."""
        import dataclasses

        n = int(np.asarray(enc.valid).sum())
        return dataclasses.replace(
            enc,
            **{
                f.name: np.asarray(getattr(enc, f.name))[:n]
                for f in dataclasses.fields(enc)
            },
        )

    def _call(self, ep, er, kernel: str, eps: float, max_iters: int):
        req = encoded_to_proto(
            self._strip_padding(ep),
            self._strip_padding(er),
            self.weights,
            kernel=kernel,
            eps=eps,
            max_iters=max_iters,
        )
        t0 = time.perf_counter()
        resp = self.client.assign(req, timeout=self.request_timeout)
        self._rtt_ms.append((time.perf_counter() - t0) * 1e3)
        self._backend_ms.append(resp.solve_ms)
        return resp

    def _bounded_t4p(self, ep, er) -> np.ndarray:
        if self.native_fallback:
            # engine=native-mt rides the wire as a kernel-string suffix so
            # the backend's warm arena (and its thread pool) do the work
            if self.native_engine == "native-mt":
                kernel = "native-mt" + (
                    f":{self.native_threads}" if self.native_threads else ""
                )
            else:
                kernel = "native"
            resp = self._call(ep, er, kernel, eps=0.02, max_iters=0)
            return np.asarray(resp.task_for_provider, np.int32)
        resp = self._call(ep, er, "auction", eps=0.05, max_iters=300)
        return np.asarray(resp.task_for_provider, np.int32)

    def _bounded_t4p_sparse(
        self, ep, er, price0: np.ndarray, p4s0: np.ndarray, warm: bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Scale path over the wire: the backend's "topk" kernel, with the
        incremental-solve state (prices + previous matching) riding the
        request/response so the backend stays stateless across replicas."""
        n_p = int(np.asarray(ep.valid).sum())
        n_s = int(np.asarray(er.valid).sum())
        req = encoded_to_proto(
            self._strip_padding(ep),
            self._strip_padding(er),
            self.weights,
            kernel="topk",
            top_k=self.top_k,
            eps=0.02,
        )
        if warm:
            req.warm_price.extend(np.asarray(price0[:n_p], np.float32).tolist())
            req.seed_provider_for_task.extend(
                np.asarray(p4s0[:n_s], np.int32).tolist()
            )
        t0 = time.perf_counter()
        resp = self.client.assign(req, timeout=self.request_timeout)
        self._rtt_ms.append((time.perf_counter() - t0) * 1e3)
        self._backend_ms.append(resp.solve_ms)
        return (
            np.asarray(resp.task_for_provider, np.int32),
            np.asarray(resp.price, np.float32),
        )

    def _unbounded_best(self, ep, er) -> np.ndarray:
        resp = self._call(ep, er, "best", eps=0.0, max_iters=0)
        return np.asarray(resp.task_for_provider, np.int32)
