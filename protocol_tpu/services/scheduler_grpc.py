"""Scheduler gRPC backend service.

The seam from BASELINE.json's north star: a control plane (the reference's
Rust orchestrator, or this repo's Python one) calls ``Assign`` with columnar
provider/requirement batches; the backend builds the cost structure on the
accelerator and returns the matching. Columnar fixed-width payloads keep the
(de)serialization cost linear in P+T — no per-entity JSON on the hot path
(SURVEY.md §7 hard part #6).

Wire revisions (the fallback ladder, newest first):

  v2 sessions  ``OpenSession`` (client-streamed snapshot) + ``AssignDelta``
               (churned rows only): the server pins the warm arena behind a
               ``(session_id, epoch_fingerprint)`` key and per-tick wire
               cost is O(churn). Refused deltas (unknown session, epoch or
               tick mismatch, evicted) drop the client one rung down.
  v2 unary     ``AssignV2``: tensor-frame batches (``TensorBlob`` columns,
               ``tobytes``/``frombuffer``, zero per-element Python work),
               full snapshot per call, stateless.
  v1 unary     ``Assign``: repeated-scalar proto fields. Frozen contract —
               old clients keep working against new servers.

Service stubs are hand-wired with grpc generic handlers (no protoc grpc
plugin needed); messages come from protocol_tpu.proto.scheduler_pb2.

Kernels: "greedy" (first-fit scan), "auction" (dense Bertsekas),
"sinkhorn" (entropic OT + rounding), "topk" (streaming candidates + sparse
frontier auction — the scale path), "native"/"native-mt" (the C++ CPU
engine; native-mt solves ride the servicer's persistent warm arena).
"""

from __future__ import annotations

import os
import time
import uuid
from concurrent import futures
from typing import NamedTuple, Optional

import grpc
import numpy as np

from protocol_tpu import obs as obs_pkg
from protocol_tpu.obs.metrics import ObsRegistry, tenant_of
from protocol_tpu.obs.spans import TRACER as _tracer, span_dicts_compact
from protocol_tpu.ops.cost import CostWeights, cost_matrix
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto.wire import (
    P_WIRE_DTYPES,
    R_WIRE_DTYPES,
    assemble_snapshot,
    canon_columns,
    chunk_snapshot,
    decode_providers_v2,
    decode_requirements_v2,
    dirty_rows,
    encode_providers_v2,
    encode_requirements_v2,
    epoch_fingerprint,
    strip_padding,
    take_rows,
    unblob,
    blob,
)
from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
from protocol_tpu.services.session_store import (
    SolveSession,
    make_solve_arena,
    parse_native_threads,
    parse_session_kernel,
    _pad_cols,
)
from protocol_tpu.utils.metrics import SeamMetrics

SERVICE_NAME = "protocol_tpu.scheduler.v1.SchedulerBackend"


def _np(arr, dtype):
    # repeated-scalar containers support the sequence protocol: fromiter
    # fills the destination buffer directly, no intermediate Python list
    return np.fromiter(arr, dtype=dtype, count=len(arr))


def providers_from_proto(msg: pb.ProviderBatch) -> EncodedProviders:
    n = len(msg.gpu_count)
    return EncodedProviders(
        gpu_count=_np(msg.gpu_count, np.int32),
        gpu_mem_mb=_np(msg.gpu_mem_mb, np.int32),
        gpu_model_id=_np(msg.gpu_model_id, np.int32),
        has_gpu=_np(msg.has_gpu, bool),
        has_cpu=_np(msg.has_cpu, bool),
        cpu_cores=_np(msg.cpu_cores, np.int32),
        ram_mb=_np(msg.ram_mb, np.int32),
        storage_gb=_np(msg.storage_gb, np.int32),
        lat=_np(msg.lat, np.float32),
        lon=_np(msg.lon, np.float32),
        has_location=_np(msg.has_location, bool),
        price=_np(msg.price, np.float32),
        load=_np(msg.load, np.float32),
        valid=np.ones(n, bool),
    )


def requirements_from_proto(msg: pb.RequirementBatch) -> EncodedRequirements:
    t = len(msg.cpu_cores)
    k = max(int(msg.max_gpu_options), 1)
    w = max(int(msg.model_words), 1)
    return EncodedRequirements(
        cpu_required=_np(msg.cpu_required, bool),
        cpu_cores=_np(msg.cpu_cores, np.int32),
        ram_mb=_np(msg.ram_mb, np.int32),
        storage_gb=_np(msg.storage_gb, np.int32),
        gpu_opt_valid=_np(msg.gpu_opt_valid, bool).reshape(t, k),
        gpu_count=_np(msg.gpu_count, np.int32).reshape(t, k),
        gpu_mem_min=_np(msg.gpu_mem_min, np.int32).reshape(t, k),
        gpu_mem_max=_np(msg.gpu_mem_max, np.int32).reshape(t, k),
        gpu_total_mem_min=_np(msg.gpu_total_mem_min, np.int32).reshape(t, k),
        gpu_total_mem_max=_np(msg.gpu_total_mem_max, np.int32).reshape(t, k),
        gpu_model_mask=_np(msg.gpu_model_mask, np.uint32).reshape(t, k, w),
        gpu_model_constrained=_np(msg.gpu_model_constrained, bool).reshape(t, k),
        lat=_np(msg.lat, np.float32),
        lon=_np(msg.lon, np.float32),
        has_location=_np(msg.has_location, bool),
        priority=_np(msg.priority, np.float32),
        valid=np.ones(t, bool),
    )


def _pad_pow2(enc, n_real: int):
    """Pad an encoded batch to the next pow2 bucket with valid=False rows:
    the wire carries only real rows (no valid mask), while bucketed shapes
    keep the backend's jit cache from recompiling per batch size."""
    import dataclasses

    if n_real <= 0:
        return enc
    target = 1 << (n_real - 1).bit_length()
    if target == n_real:
        return enc
    out = {}
    for f in dataclasses.fields(enc):
        a = np.asarray(getattr(enc, f.name))
        pad = [(0, target - n_real)] + [(0, 0)] * (a.ndim - 1)
        out[f.name] = np.pad(a, pad)
    out["valid"] = np.concatenate(
        [np.ones(n_real, bool), np.zeros(target - n_real, bool)]
    )
    return dataclasses.replace(enc, **out)


def _delta_crc(request: "pb.AssignDeltaRequest") -> int:
    """Byte-exact identity of one delta tick WITHOUT re-serializing the
    just-deserialized message (that would add O(delta bytes) of encode
    work to every tick inside the session lock): CRC over the tick
    cursor plus every blob's already-materialized raw bytes — the only
    payload a retransmitted delta can differ in. The idempotent-
    retransmit dedup (and the checkpointed cursor it survives restarts
    through) rests on this identity."""
    import zlib

    crc = zlib.crc32(int(request.tick).to_bytes(8, "little"))  # lint: unlocked-ok (protobuf field, not session state)
    for b in (request.provider_rows, request.task_rows):
        crc = zlib.crc32(b.data, crc)
    for batch in (request.providers, request.requirements):
        for nt in batch.columns:
            crc = zlib.crc32(nt.name.encode(), crc)
            crc = zlib.crc32(nt.tensor.data, crc)
    return crc


def _stream_gap_ceiling() -> Optional[float]:
    """Server-side certified-gap ceiling for streaming sessions
    (PROTOCOL_TPU_STREAM_GAP_CEILING): when the streamed plan's
    certified optimality gap crosses it, the engine reconciles inline
    instead of serving the drifted plan. Unset = cadence-only
    reconciliation."""
    raw = os.environ.get("PROTOCOL_TPU_STREAM_GAP_CEILING", "").strip()
    return float(raw) if raw else None


class _SolveOut(NamedTuple):
    """Kernel output over the REAL (unpadded) row counts."""

    p4t: np.ndarray  # [T] i32, -1 = unassigned
    t4p: np.ndarray  # [P] i32, -1 = idle
    num_assigned: int
    price: Optional[np.ndarray]  # [P] f32 (sparse/native kernels)
    # the warm arena's last_stats, COPIED under the arena lock (reading
    # it later would race the next unary solve) — obs/trace provenance
    arena_stats: Optional[dict] = None


class SchedulerBackendServicer:
    def __init__(
        self,
        max_sessions: int = 8,
        session_ttl_s: float = 900.0,
        fleet=None,
        slo=None,
    ):
        from protocol_tpu.sched.cand_cache import CandidateMemo

        self._cand_memo = CandidateMemo()
        # persistent warm arena for the unary "native-mt"/"sinkhorn-mt"
        # kernels: steady-state Assign repeats (the heartbeat loop's
        # byte-identical or lightly churned fleets) reuse the candidate
        # structure + solver duals and recompute only dirty rows — the
        # native twin of _cand_memo's delta-awareness, but incremental
        # rather than exact-repeat-only.
        #
        # Locking is SHARDED, not global: this lock guards only the unary
        # path's shared arena (which mutates carried state in place — one
        # arena, necessarily serialized). Session solves take their OWN
        # ``session.lock`` (services/session_store.py), so two delta
        # sessions never serialize each other; what they share instead is
        # the bounded EngineThreadBudget below, which keeps N concurrent
        # solves from oversubscribing the host by N x "all hardware
        # threads" (grants are thread-count invariant by the engines'
        # determinism contract, so borrowing fewer threads never changes
        # a matching).
        self._native_arena = None
        from protocol_tpu.utils.lockwitness import make_lock

        self._unary_arena_lock = make_lock("arena")
        # ---- fleet layer (always on; the defaults are transparent):
        # sessions live in a consistent-hash sharded fabric (each shard
        # its own lock domain, global count/byte budgets enforced by
        # cross-shard LRU pressure), engine threads come from the
        # weighted-fair budget (bit-compatible with the base budget for
        # a sole tenant), and per-tenant token buckets gate admission
        # (rate=None admits everything but still counts). ``fleet`` is
        # a FleetConfig; None reads PROTOCOL_TPU_FLEET_* from the env.
        from protocol_tpu.fleet import (
            FairThreadBudget,
            FleetConfig,
            SessionFabric,
            TenantAdmission,
        )

        cfg = fleet if fleet is not None else FleetConfig.from_env()
        self.fleet_config = cfg
        self._engine_budget = FairThreadBudget(weights=cfg.tenant_weights)
        self.sessions = SessionFabric(
            shards=cfg.shards,
            max_sessions=max_sessions,
            ttl_s=session_ttl_s,
            max_bytes=cfg.max_bytes,
            tenant_max_bytes=cfg.tenant_max_bytes,
            vnodes=cfg.vnodes,
        )
        self.admission = TenantAdmission(
            rate=cfg.admit_rate, burst=cfg.admit_burst
        )
        self.seam = SeamMetrics(role="server")
        # observability plane: per-session tick histograms (true
        # p50/p99/p999), assigned fraction, arena reuse ratio, plus
        # budget/store gauges read at scrape time. The dict snapshot is
        # authoritative; /metrics is wired by serve(metrics_port=...).
        self.obs = ObsRegistry(role="server")
        # SLO engine (obs/slo.py): declarative per-tenant objectives
        # evaluated with tick-indexed multi-window burn rates inside
        # observe_tick; ``slo`` is an SLOConfig, None reads the
        # PROTOCOL_TPU_SLO_* env vars (all-unset = inert)
        from protocol_tpu.obs.slo import SLOConfig, SLOEngine

        self.slo = SLOEngine(
            slo if slo is not None else SLOConfig.from_env()
        )
        self.obs.attach(
            budget=self._engine_budget,
            store=self.sessions,
            fleet=self.sessions,
            admission=self.admission,
            slo=self.slo,
            proc_id=cfg.proc_id,
        )
        # flight recorder (PROTOCOL_TPU_TRACE=<path>): any solve served by
        # this backend records its exact inputs + outcomes — unary calls
        # via the column differ, the session protocol via its own wire
        # frames (see protocol_tpu/trace/recorder.py). Best-effort: a
        # capture failure never fails an RPC.
        self.trace = None
        if os.environ.get("PROTOCOL_TPU_TRACE"):
            from protocol_tpu.trace.recorder import TraceRecorder

            self.trace = TraceRecorder.from_env("server")
        # ---- resilience layer (chaos plane). With ``ckpt_dir`` set,
        # every session keeps a crash-atomic on-disk twin (flushed on
        # the tick cadence BEFORE the tick is acknowledged), and a
        # fresh servicer REHYDRATES them here: after a crash+restart
        # the client's next AssignDelta resumes at the checkpointed
        # cursor instead of being refused into a full-snapshot reopen
        # herd. ``draining`` is the SIGTERM drain flag: OpenSession
        # stops admitting, in-flight ticks finish, checkpoints flush.
        self.draining = False
        self.ckpt = None
        # ---- distributed fleet (dfleet) router state. ``_moved`` maps
        # a migrated-away session to the endpoint now serving it — the
        # "moved:<endpoint>" redirect answer the client ladder follows
        # warm. ``_no_rehydrate`` tombstones sessions this process
        # itself evicted (lru/pressure/chaos): eviction exists to
        # RELEASE memory, so the lazy journal rehydrate below must not
        # resurrect the victim on its next delta — the PR 9 contract
        # (eviction = one counted reopen) stands. Both are bounded and
        # guarded by the leaf ``router`` lock (dict ops only, safely
        # acquirable from under a shard lock in the eviction callback).
        from collections import OrderedDict as _ODict

        self._router_lock = make_lock("router")
        self._moved: "_ODict[str, str]" = _ODict()
        self._no_rehydrate: "_ODict[str, bool]" = _ODict()
        self._rehydrating: set = set()
        self._migrating: set = set()
        self.proc_id = cfg.proc_id
        self.endpoint = cfg.endpoint
        if cfg.ckpt_dir:
            from protocol_tpu.faults.checkpoint import SessionCheckpointer

            self.ckpt = SessionCheckpointer(
                cfg.ckpt_dir, every=cfg.ckpt_every, proc_id=cfg.proc_id
            )
            # newest-first, capped at the session budget: stale files
            # must never crowd the restore past max_sessions (the put
            # pressure below would then LRU-evict restored sessions)
            for session in self.ckpt.load_all(
                budget=self._engine_budget, limit=max_sessions
            ):
                self.sessions.put(session)
                self.seam.count("session_restored")
            # checkpoint GC: a ttl-expired or client-dropped session's
            # client is GONE — its file would only resurrect a dead
            # session at every restart, growing ckpt_dir without bound.
            # lru/pressure/replace keep their files: the session is
            # alive client-side (or the file already belongs to the
            # same-id successor, which flushed over it at open). Every
            # OTHER involuntary let-go additionally tombstones the
            # session against LAZY rehydration (see _router_lock note).
            def _ckpt_gc(session, reason: str) -> None:
                if reason in ("ttl", "drop"):
                    self.ckpt.drop(session.session_id)
                elif reason not in ("migrate", "replace"):
                    self._router_tombstone(session.session_id)

            self.sessions.on_let_go = _ckpt_gc

    # ---------------- dfleet router surface ----------------

    _ROUTER_CAP = 4096  # bound for the moved/tombstone maps (client-
    # minted session ids; same rationale as fabric._MAX_TENANT_KEYS)

    def _router_tombstone(self, session_id: str) -> None:
        with self._router_lock:
            self._no_rehydrate[session_id] = True
            while len(self._no_rehydrate) > self._ROUTER_CAP:
                self._no_rehydrate.popitem(last=False)

    def _router_adopt(self, session_id: str) -> None:
        """A session was (re)opened or rehydrated HERE: this process
        owns it now — clear any stale redirect/tombstone so its deltas
        are served, not bounced."""
        with self._router_lock:
            self._moved.pop(session_id, None)
            self._no_rehydrate.pop(session_id, None)

    def _moved_to(self, session_id: str) -> Optional[str]:
        """Where this session was migrated to, or None. The JOURNAL'S
        LOCATION is the authority and the redirect map only a cache: if
        the journal is back in OUR namespace (the target died and the
        ring re-routed it here), the stale redirect would bounce
        clients at a corpse forever — adopt the session back instead."""
        with self._router_lock:
            moved = self._moved.get(session_id)
            in_flight = session_id in self._migrating
        if moved is None:
            return None
        # in-flight migration: the journal is legitimately still here
        # (flush happens after the redirect is recorded) — the redirect
        # stands, and the client's handoff-wait rung covers the rename
        if not in_flight and self.ckpt is not None and os.path.exists(
            self.ckpt.path_for(session_id)
        ):
            self._router_adopt(session_id)
            return None
        return moved

    def _fence_route(self, session_id: str) -> Optional[str]:
        """None = this process's journal fence is intact (the normal
        case — one stat call). Otherwise the namespace's fencing epoch
        was SUPERSEDED while this process wasn't looking (SIGSTOP
        zombie resuming after a detector ejection, partitioned node):
        its journals were re-routed along the ring, so it must neither
        ack nor admit — split-brain is refused by construction. Returns
        the session's new home endpoint per the fence-stamped topology,
        or "" when the stamp carries no usable route (the client
        re-opens down the ladder, counted)."""
        if self.ckpt is None or not self.ckpt.fence_superseded():
            return None
        self.seam.count("fence_refused")
        topo = self.ckpt.fence_state().get("topology")
        if topo:
            try:
                from protocol_tpu.dfleet.topology import FleetTopology

                ep = FleetTopology.from_dict(topo).endpoint_for(
                    session_id
                )
                if ep and ep != self.endpoint:
                    return ep
            except Exception:  # torn/foreign stamp: fall through
                pass
        return ""

    def _rehydrate(self, session_id: str, fingerprint: str):
        """Lazy warm restore behind a delta miss: if this process's
        journal namespace holds the session (a migration handoff landed
        it here, or a crash-restart's boot cap skipped it), load and
        adopt it. None = nothing to restore (the caller answers the
        miss normally). Single-flight per session id: a concurrent miss
        returns None and rides the client's bounded handoff-wait rung."""
        if self.ckpt is None:
            return None
        with self._router_lock:
            if (
                session_id in self._no_rehydrate
                or session_id in self._moved
                or session_id in self._rehydrating
            ):
                return None
            self._rehydrating.add(session_id)
        try:
            loaded = self.ckpt.load_one(
                session_id, budget=self._engine_budget
            )
            if loaded is None:
                return None
            self.sessions.put(loaded)
            self.seam.count("session_rehydrated")
        finally:
            with self._router_lock:
                self._rehydrating.discard(session_id)
        session, _ = self.sessions.get(session_id, fingerprint)
        return session

    def migrate_out(
        self,
        target_endpoint: str,
        target_proc_id: str,
        session_ids=None,
    ) -> int:
        """Live-drain sessions onto another process: record the
        redirect FIRST (a delta racing the eviction is answered
        "moved:", never "unknown"), evict (in-flight solves refuse via
        the evicted flag), flush the journal at its final tick, and
        hand it off atomically into the target's namespace. The target
        rehydrates each session warm on its first redirected delta —
        zero client reopens, and the tick-cursor/CRC dedup carries the
        retransmit guarantee across the boundary."""
        if self.ckpt is None:
            return 0
        wanted = set(session_ids) if session_ids else None
        moved = 0
        for session in self.sessions.snapshot_sessions():
            sid = session.session_id
            if wanted is not None and sid not in wanted:
                continue
            with self._router_lock:
                self._moved[sid] = target_endpoint
                self._migrating.add(sid)
                while len(self._moved) > self._ROUTER_CAP:
                    self._moved.popitem(last=False)
            try:
                self.sessions.shard_of(sid).evict(sid, reason="migrate")
                with session.lock:
                    flushed = self.ckpt.flush_locked(session)
                if not flushed or not self.ckpt.handoff(
                    sid, target_proc_id
                ):
                    # no journal to move (flush failed / never
                    # flushed): drop the redirect — the client's ladder
                    # re-opens at the target instead of chasing a
                    # journal that is not there (counted, explicit, the
                    # pre-dfleet contract)
                    with self._router_lock:
                        self._moved.pop(sid, None)
                    continue
            finally:
                with self._router_lock:
                    self._migrating.discard(sid)
            moved += 1
            self.seam.count("session_migrated_out")
        return moved

    def Migrate(
        self, request: pb.MigrateRequest, context
    ) -> pb.MigrateResponse:
        """Admin surface for live migration (the dfleet manager and
        rolling-upgrade drills call this; it is not on any client hot
        path)."""
        with self._rpc_span("rpc.Migrate", context):
            if not request.target_endpoint or not request.target_proc_id:
                return pb.MigrateResponse(
                    ok=False,
                    error="UNAVAILABLE: migrate needs target_endpoint "
                          "and target_proc_id",
                )
            if self.ckpt is None:
                return pb.MigrateResponse(
                    ok=False,
                    error="UNAVAILABLE: no checkpoint journal "
                          "configured (ckpt_dir unset) — nothing to "
                          "hand off",
                )
            moved = self.migrate_out(
                request.target_endpoint,
                request.target_proc_id,
                list(request.session_ids) or None,
            )
            return pb.MigrateResponse(ok=True, moved=moved)

    # ---------------- shared kernel dispatch ----------------

    def _solve(
        self,
        ep: EncodedProviders,
        er: EncodedRequirements,
        weights: CostWeights,
        kernel: str,
        top_k: int,
        eps: float,
        max_iters: int,
        warm_price: Optional[np.ndarray],
        seed_p4t: Optional[np.ndarray],
        context,
    ) -> _SolveOut:
        """One solve over unpadded encoded batches: pads to the pow2
        bucket, dispatches the kernel, slices back to real row counts.
        Shared verbatim by the v1 and v2 surfaces — wire parity is a
        property of the codec, never of the kernel path."""
        P = int(np.asarray(ep.gpu_count).shape[0])
        T = int(np.asarray(er.cpu_cores).shape[0])
        if P == 0 or T == 0:
            # degenerate batches are legal: nothing to match
            return _SolveOut(
                np.full(T, -1, np.int32), np.full(P, -1, np.int32), 0, None
            )
        # bucket the batch (valid=False padding rows) so repeat calls reuse
        # the jit cache; replies are sliced back to the real row counts, and
        # padding rows are infeasible by mask so they never win assignments
        ep = _pad_pow2(ep, P)
        er = _pad_pow2(er, T)

        if kernel == "best":
            # per-provider argmin over compatible tasks: the one-to-many
            # unbounded phase of the batch matcher (many providers may pick
            # the same task, so this is not a matching kernel)
            from protocol_tpu.sched.tpu_backend import _solve_unbounded

            best, _feas = _solve_unbounded(ep, er, weights)
            t4p = np.asarray(best)[:P].astype(np.int32)
            return _SolveOut(
                np.full(T, -1, np.int32), t4p, int((t4p >= 0).sum()), None
            )

        if kernel == "native" or kernel.startswith(
            ("native-mt", "sinkhorn-mt", "jax")
        ):
            # the engines behind the seam: "native" is the
            # single-threaded Gauss-Seidel solve, "native-mt[:N]" the
            # multi-threaded auction engine, "sinkhorn-mt[:N]" the
            # sparse entropic engine, and "jax[:D]" the accelerator-path
            # arena (D sharded-gen devices), all but "native" through
            # the servicer's persistent warm arena (N threads; absent/0
            # = all hardware threads / all visible devices — the suffix
            # spelling keeps the wire message unchanged)
            from protocol_tpu import native as native_mod

            p_padded = int(np.asarray(ep.gpu_count).shape[0])
            if kernel == "native":
                cand_p, cand_c = native_mod.fused_topk_candidates(
                    ep, er, weights,
                    k=min(max(top_k or 64, 1), p_padded),
                )
                p4t_full = native_mod.auction_sparse(
                    cand_p, cand_c, num_providers=p_padded
                )
                price_full = np.zeros(p_padded, np.float32)
            else:
                parsed = parse_session_kernel(kernel)
                if parsed is None:
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"bad native engine thread suffix {kernel!r}",
                    )
                engine, threads = parsed
                requested_k = max(top_k or 64, 1)
                # thread grant is borrowed INSIDE the arena lock: the
                # unary arena is one serialized resource, so a request
                # parked on the lock must hold NOTHING — a pre-lock grant
                # would reserve idle threads for the whole duration of
                # the running solve, starving concurrent session solves
                # (which draw on the same budget from their own locks).
                # No deadlock: budget holders never need this lock.
                with self._unary_arena_lock:
                    if (
                        self._native_arena is None
                        or self._native_arena.k != requested_k
                        or self._native_arena.engine != engine
                    ):
                        # a changed k or engine changes the whole
                        # carried structure: a fresh arena (cold
                        # solve) is the only honest answer
                        from protocol_tpu.services.session_store import (
                            make_solve_arena,
                        )

                        self._native_arena = make_solve_arena(
                            engine, k=requested_k, threads=threads,
                        )
                    grant = self._engine_budget.acquire(threads, "unary")
                    try:
                        self._native_arena.threads = grant
                        p4t_full = self._native_arena.solve(
                            ep, er, weights
                        )
                        price_full = self._native_arena.price
                        arena_stats = dict(self._native_arena.last_stats)
                    finally:
                        self._engine_budget.release(grant, "unary")
            if kernel == "native":
                arena_stats = None
            p4t = np.asarray(p4t_full)[:T]
            t4p = np.full(P, -1, np.int32)
            seated = np.flatnonzero((p4t >= 0) & (p4t < P))
            t4p[p4t[seated]] = seated.astype(np.int32)
            return _SolveOut(
                p4t, t4p, int((p4t >= 0).sum()),
                np.asarray(price_full)[:P].astype(np.float32),
                arena_stats,
            )

        if kernel == "topk":
            from protocol_tpu.ops.sparse import (
                assign_auction_sparse_scaled,
                assign_auction_sparse_warm,
            )

            # tile must divide the (padded, pow2) T
            t_padded = int(np.asarray(er.cpu_cores).shape[0])
            tile = min(1024, t_padded)
            while t_padded % tile != 0:
                tile -= 1
            p_padded = int(np.asarray(ep.gpu_count).shape[0])
            # bidirectional: same coverage-safe generator as the in-process
            # matcher (_bounded_t4p_sparse) — remote/in-process parity.
            # Content-hash memoized: the steady-state heartbeat loop sends
            # a byte-identical fleet, and the stateless seam must not
            # re-pay the O(P*T) generation for it (VERDICT r4 item 3)
            cand_p, cand_c = self._cand_memo.get(
                ep, er, weights,
                k=max(top_k or 64, 1), tile=tile,
                reverse_r=8, extra=16,
            )
            if (
                warm_price is not None and seed_p4t is not None
                and len(warm_price) == P and len(seed_p4t) == T
            ):
                # stateless incremental solve: warm state rode the wire.
                # Wire input is untrusted: clamp out-of-range seeds and
                # drop duplicates (the warm kernel requires injectivity
                # over >= 0 — a duplicated provider index would produce a
                # corrupt two-tasks-one-provider "matching").
                price0 = np.zeros(p_padded, np.float32)
                price0[:P] = np.nan_to_num(
                    np.asarray(warm_price, np.float32),
                    nan=0.0, posinf=0.0, neginf=0.0,
                )
                p4t0 = np.full(t_padded, -1, np.int32)
                seeds = np.asarray(seed_p4t, np.int32).copy()
                seeds = np.where((seeds >= 0) & (seeds < P), seeds, -1)
                pos = seeds >= 0
                _, first = np.unique(seeds[pos], return_index=True)
                keep = np.zeros(int(pos.sum()), bool)
                keep[first] = True
                seeds[np.flatnonzero(pos)[~keep]] = -1
                p4t0[:T] = seeds
                res, price = assign_auction_sparse_warm(
                    cand_p, cand_c, p_padded,
                    price0=price0, p4t0=p4t0,
                    eps=eps or 0.02,
                    max_iters=max_iters or 20000,
                )
            else:
                res, price = assign_auction_sparse_scaled(
                    cand_p, cand_c, p_padded,
                    eps_end=eps or 0.02,
                    max_iters_per_phase=max_iters or 4000,
                    with_prices=True,
                )
            p4t = np.asarray(res.provider_for_task)[:T]
            t4p = np.asarray(res.task_for_provider)[:P]
            return _SolveOut(
                p4t, t4p, int((p4t >= 0).sum()),
                np.asarray(price)[:P].astype(np.float32),
            )

        from protocol_tpu.ops.assign import (
            assign_auction,
            assign_greedy,
            assign_sinkhorn,
        )

        cost, _ = cost_matrix(ep, er, weights)
        if kernel == "greedy":
            res = assign_greedy(cost)
        elif kernel == "sinkhorn":
            res = assign_sinkhorn(
                cost,
                eps=eps or 0.05,
                num_iters=max_iters or 200,
            )
        elif kernel == "auction":
            from protocol_tpu.ops.cost import with_tie_jitter

            # same degeneracy breaker as the in-process dense solve
            # (sched/tpu_backend._solve_bounded) — identical jitter is
            # what RemoteBatchMatcher's parity with TpuBatchMatcher
            # rests on
            res = assign_auction(
                with_tie_jitter(cost),
                eps=eps or 0.01,
                max_iters=max_iters or 500,
            )
        else:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"unknown kernel {kernel!r}"
            )
        p4t = np.asarray(res.provider_for_task)[:T]
        t4p = np.asarray(res.task_for_provider)[:P]
        return _SolveOut(p4t, t4p, int((p4t >= 0).sum()), None)

    @staticmethod
    def _weights_of(request) -> CostWeights:
        if request.HasField("weights"):
            # submessage presence is real in proto3: a set weights message
            # is used verbatim, so a legitimate 0.0 weight survives the wire
            return CostWeights(
                price=request.weights.price,
                load=request.weights.load,
                proximity=request.weights.proximity,
                priority=request.weights.priority,
            )
        return CostWeights()

    # ---------------- observability helpers ----------------

    def _rpc_span(self, name: str, context, **attrs):
        """Root span for one RPC, adopting the client's trace context
        from the ``x-pt-span`` metadata header so a client tick stitches
        into one causal trace across the seam. Tolerates a None/bare
        context (tests drive servicer methods directly)."""
        md = (
            context.invocation_metadata()
            if context is not None
            and hasattr(context, "invocation_metadata")
            else None
        )
        return _tracer.span(
            name, remote_parent=_tracer.extract(md), **attrs,
        )

    @staticmethod
    def _enrich_metrics(
        base: dict, arena_stats: Optional[dict], mark: int, root,
    ) -> dict:
        """Outcome-frame metrics: the base phase numbers plus the
        arena's scalar stats (incl. the flattened ``eng_*`` native
        phase stats) and the spans this RPC completed — what the obs
        report renders offline."""
        m = dict(base)
        if arena_stats:
            for k, v in arena_stats.items():
                # base keys (the RPC-level decode/solve walls) win over
                # arena keys of the same name (stage-level walls): the
                # stage split still rides in gen_ms + the eng_* phases
                if k not in m and isinstance(v, (int, float, bool, str)):
                    m[k] = v
        if root is not None:
            sp = _tracer.since(mark, trace=root["trace"])
            if sp:
                m["trace_id"] = root["trace"]
                m["spans"] = span_dicts_compact(sp)
        return m

    def _observe_tick(
        self,
        session_id: str,
        t0: float,
        n_tasks: int,
        num_assigned: int,
        arena_stats: Optional[dict] = None,
        delta_rows: int = 0,
        trace_tick: Optional[int] = None,
    ) -> list:
        """Returns the SLO alert events this tick fired/cleared (empty
        without a configured SLO engine or a breach) — the caller lands
        them in the trace as event frames. ``trace_tick`` anchors the
        EVENT frame at the caller's wire tick (session paths MUST pass
        it: this runs after the session lock is released, so a pipelined
        delta may already have advanced the recorder's stream tick)."""
        from protocol_tpu import obs

        if not obs.enabled():
            # PROTOCOL_TPU_OBS=0 turns the WHOLE plane off — per-session
            # registries included, not just spans and engine stats
            return []
        alerts = self.obs.observe_tick(
            session_id, (time.perf_counter() - t0) * 1e3, n_tasks,
            num_assigned, arena_stats=arena_stats, delta_rows=delta_rows,
        )
        if alerts and self.trace is not None:
            from protocol_tpu.trace.recorder import safe as _trace_safe

            # structured breach events ride the flight recorder too, so
            # replay/report can show WHEN the quality plane paged. The
            # unary registry keys ("unary:v1"/"unary:v2") are NOT trace
            # stream owners — column-mode streams are unowned (None);
            # the recorder drops events whose owner doesn't match its
            # stream, so alerts never land in a different workload's
            # trace
            _trace_safe(
                self.trace.record_events, alerts,
                session_id=(
                    None if session_id.startswith("unary:") else session_id
                ),
                tick=trace_tick,
            )
        return alerts

    # ---------------- v1 unary (frozen contract) ----------------

    def Assign(self, request: pb.AssignRequest, context) -> pb.AssignResponse:
        mark = _tracer.mark()
        with self._rpc_span("rpc.Assign", context, wire="v1") as root:
            return self._assign_v1(request, context, mark, root)

    def _admit_unary(self, context) -> None:
        """Admission gate for the stateless rungs. Without this, a
        tenant refused on the session protocol would fall to unary and
        run UNTHROTTLED — the fallback ladder would bypass admission.
        Unary carries no session id, so all unary traffic shares one
        "unary" bucket (coarse by design; rate=None, the default, is a
        no-op). Refusal is a gRPC RESOURCE_EXHAUSTED status — an
        explicit throttle the caller sees, never a silent drop."""
        if not self.admission.admit("unary"):
            self.seam.count("admission_refused")
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                "unary admission rate exceeded",
            )

    def _check_deadline(self, context, where: str) -> None:
        """Honor the caller's gRPC deadline/cancellation BEFORE a solve
        is dispatched: a client that hung up (or whose deadline is
        already burned) must not keep consuming engine threads — its
        answer is undeliverable either way. Tolerates bare/fake
        contexts (tests drive servicer methods directly)."""
        if context is None:
            return
        is_active = getattr(context, "is_active", None)
        if callable(is_active) and not context.is_active():
            self.seam.count("deadline_refused")
            context.abort(
                grpc.StatusCode.CANCELLED,
                f"client cancelled before the {where} solve",
            )
        time_remaining = getattr(context, "time_remaining", None)
        if callable(time_remaining):
            remaining = context.time_remaining()
            if remaining is not None and remaining <= 0:
                self.seam.count("deadline_refused")
                context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    f"deadline burned before the {where} solve",
                )

    def _assign_v1(
        self, request: pb.AssignRequest, context, mark: int, root
    ) -> pb.AssignResponse:
        self._admit_unary(context)
        t0 = time.perf_counter()
        with _tracer.span("wire.decode", wire="v1"):
            ep = providers_from_proto(request.providers)
            er = requirements_from_proto(request.requirements)
        t_dec = time.perf_counter()
        warm = seeds = None
        if len(request.warm_price) or len(request.seed_provider_for_task):
            warm = _np(request.warm_price, np.float32)
            seeds = _np(request.seed_provider_for_task, np.int32)
        kernel = request.kernel or "auction"
        self._check_deadline(context, "v1 unary")
        with _tracer.span("engine.solve", kernel=kernel):
            out = self._solve(
                ep, er, self._weights_of(request), kernel,
                int(request.top_k), request.eps, int(request.max_iters),
                warm, seeds, context,
            )
        t_solve = time.perf_counter()
        self.seam.observe_ms("decode", (t_dec - t0) * 1e3)
        self.seam.observe_ms("solve", (t_solve - t_dec) * 1e3)
        self.seam.add_bytes("in", request.ByteSize())
        with _tracer.span("wire.encode", wire="v1"):
            resp = pb.AssignResponse(
                provider_for_task=out.p4t.astype(np.int32),
                task_for_provider=out.t4p.astype(np.int32),
                num_assigned=out.num_assigned,
                solve_ms=(time.perf_counter() - t0) * 1e3,
            )
            if out.price is not None:
                resp.price.extend(out.price)
        self.seam.add_bytes("out", resp.ByteSize())
        arena_stats = out.arena_stats
        self._observe_tick(
            "unary:v1", t0, out.p4t.shape[0], out.num_assigned, arena_stats
        )
        if self.trace is not None:
            from protocol_tpu.trace.recorder import safe as _trace_safe

            _trace_safe(
                self.trace.record_solve, ep, er, self._weights_of(request),
                kernel, int(request.top_k),
                request.eps, int(request.max_iters), out.p4t, out.price,
                metrics=self._enrich_metrics({
                    "decode_ms": round((t_dec - t0) * 1e3, 3),
                    "solve_ms": round((t_solve - t_dec) * 1e3, 3),
                    "bytes_in": request.ByteSize(),
                    "bytes_out": resp.ByteSize(),
                    "wire": "v1",
                }, arena_stats, mark, root),
            )
        return resp

    # ---------------- v2 unary: tensor frames ----------------

    def AssignV2(
        self, request: pb.AssignRequestV2, context
    ) -> pb.AssignResponseV2:
        mark = _tracer.mark()
        with self._rpc_span("rpc.AssignV2", context, wire="v2") as root:
            return self._assign_v2(request, context, mark, root)

    def _assign_v2(
        self, request: pb.AssignRequestV2, context, mark: int, root
    ) -> pb.AssignResponseV2:
        self._admit_unary(context)
        t0 = time.perf_counter()
        try:
            with _tracer.span("wire.decode", wire="v2"):
                ep = decode_providers_v2(request.providers)
                er = decode_requirements_v2(request.requirements)
                warm = (
                    unblob(request.warm_price, np.float32)
                    if request.HasField("warm_price") else None
                )
                seeds = (
                    unblob(request.seed_provider_for_task, np.int32)
                    if request.HasField("seed_provider_for_task") else None
                )
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        t_dec = time.perf_counter()
        kernel = request.kernel or "auction"
        self._check_deadline(context, "v2 unary")
        with _tracer.span("engine.solve", kernel=kernel):
            out = self._solve(
                ep, er, self._weights_of(request), kernel,
                int(request.top_k), request.eps, int(request.max_iters),
                warm, seeds, context,
            )
        t_solve = time.perf_counter()
        self.seam.observe_ms("decode", (t_dec - t0) * 1e3)
        self.seam.observe_ms("solve", (t_solve - t_dec) * 1e3)
        self.seam.add_bytes("in", request.ByteSize())
        with _tracer.span("wire.encode", wire="v2"):
            resp = self._result_v2(out, t0, t_dec - t0)
        self.seam.add_bytes("out", resp.ByteSize())
        arena_stats = out.arena_stats
        self._observe_tick(
            "unary:v2", t0, out.p4t.shape[0], out.num_assigned, arena_stats
        )
        if self.trace is not None:
            from protocol_tpu.trace.recorder import safe as _trace_safe

            _trace_safe(
                self.trace.record_solve, ep, er, self._weights_of(request),
                kernel, int(request.top_k),
                request.eps, int(request.max_iters), out.p4t, out.price,
                metrics=self._enrich_metrics({
                    "decode_ms": round((t_dec - t0) * 1e3, 3),
                    "solve_ms": round((t_solve - t_dec) * 1e3, 3),
                    "bytes_in": request.ByteSize(),
                    "bytes_out": resp.ByteSize(),
                    "wire": "v2",
                }, arena_stats, mark, root),
            )
        return resp

    @staticmethod
    def _result_v2(
        out: _SolveOut, t0: float, decode_s: float
    ) -> pb.AssignResponseV2:
        resp = pb.AssignResponseV2(
            provider_for_task=blob(out.p4t, np.int32),
            task_for_provider=blob(out.t4p, np.int32),
            num_assigned=out.num_assigned,
            solve_ms=(time.perf_counter() - t0) * 1e3,
            decode_ms=decode_s * 1e3,
        )
        if out.price is not None:
            resp.price.CopyFrom(blob(out.price, np.float32))
        return resp

    # ---------------- v2 sessions: streamed snapshot + deltas ----------

    def OpenSession(self, request_iterator, context) -> pb.OpenSessionResponse:
        mark = _tracer.mark()
        with self._rpc_span("rpc.OpenSession", context) as root:
            return self._open_session(request_iterator, context, mark, root)

    def _open_session(
        self, request_iterator, context, mark: int, root
    ) -> pb.OpenSessionResponse:
        t0 = time.perf_counter()
        try:
            with _tracer.span("wire.decode", wire="v2-session"):
                session_id, claimed_fp, req, wire_bytes = assemble_snapshot(
                    request_iterator
                )
        except ValueError as e:
            return pb.OpenSessionResponse(ok=False, error=str(e))
        self.seam.add_bytes("in", wire_bytes)
        if self.draining:
            # SIGTERM drain: stop ADMITTING — in-flight sessions keep
            # ticking until the server stops. A transient refusal on
            # the protocol surface, not a capability one: the client
            # ladder degrades this tick to unary and keeps the session
            # protocol available for the replacement server.
            self.seam.count("drain_refused")
            return pb.OpenSessionResponse(
                ok=False,
                error="UNAVAILABLE: draining, not admitting new "
                      "sessions (retry against the replacement)",
            )
        if session_id:
            fenced = self._fence_route(session_id)
            if fenced is not None:
                # this process was EJECTED (fence superseded): it must
                # not admit sessions against a namespace it no longer
                # owns — even a zombie that resumed serving
                if fenced:
                    return pb.OpenSessionResponse(
                        ok=False, error=f"moved:{fenced}"
                    )
                return pb.OpenSessionResponse(
                    ok=False,
                    error="unknown session (journal fence superseded)",
                )
            moved = self._moved_to(session_id)
            if moved is not None:
                # dfleet: this session was live-migrated away — even a
                # re-open belongs at its new home (opening it HERE would
                # fork ownership: two processes each believing they hold
                # the authoritative arena)
                self.seam.count("moved_refused")
                return pb.OpenSessionResponse(
                    ok=False, error=f"moved:{moved}"
                )
        # tenant admission BEFORE the expensive decode + cold solve: an
        # over-rate tenant costs the server one token-bucket check, not
        # a snapshot decode. The refusal is a protocol answer on the
        # existing surface — the client's ladder falls to unary v2.
        tenant = tenant_of(session_id) if session_id else "unknown"
        if not self.admission.admit(tenant):
            self.seam.count("admission_refused")
            return pb.OpenSessionResponse(
                ok=False,
                error=f"RESOURCE_EXHAUSTED: tenant {tenant!r} over "
                      "admission rate (OpenSession)",
            )
        kernel = req.kernel or "native-mt"
        parsed = parse_session_kernel(kernel)
        if parsed is None:
            # the session protocol's warm state lives in the native arena;
            # other kernels stay on the stateless unary rungs
            return pb.OpenSessionResponse(
                ok=False,
                error=f"kernel {kernel!r} is not session-servable "
                      "(want native-mt[:N] | sinkhorn-mt[:N] | jax[:D])",
            )
        engine, threads = parsed
        try:
            ep = decode_providers_v2(req.providers)
            er = decode_requirements_v2(req.requirements)
        except ValueError as e:
            return pb.OpenSessionResponse(ok=False, error=str(e))
        weights = self._weights_of(req)
        top_k = max(int(req.top_k) or 64, 1)
        p_cols = canon_columns(ep, P_WIRE_DTYPES)
        r_cols = canon_columns(er, R_WIRE_DTYPES)
        fp = epoch_fingerprint(
            p_cols, r_cols, weights, kernel, top_k, req.eps,
            int(req.max_iters),
        )
        if claimed_fp and claimed_fp != fp:
            self.seam.count("fingerprint_mismatch")
            return pb.OpenSessionResponse(
                ok=False,
                error="epoch fingerprint mismatch between client and "
                      "server codecs",
            )
        n_p = p_cols["gpu_count"].shape[0]
        n_t = r_cols["cpu_cores"].shape[0]
        from protocol_tpu.fleet import estimate_arena_bytes

        padded_p = _pad_cols(p_cols, n_p)
        padded_r = _pad_cols(r_cols, n_t)
        session = SolveSession(
            session_id=session_id or uuid.uuid4().hex,
            fingerprint=fp,
            weights=weights,
            kernel=kernel,
            threads=threads,
            top_k=top_k,
            p_cols=padded_p,
            r_cols=padded_r,
            n_providers=n_p,
            n_tasks=n_t,
            arena=make_solve_arena(engine, k=top_k, threads=threads),
            budget=self._engine_budget,
            # fleet arena budget: rows x dtype widths, estimated once
            arena_bytes=estimate_arena_bytes(padded_p, padded_r, top_k),
        )
        t_dec = time.perf_counter()
        self._check_deadline(context, "session-open")
        with _tracer.span("engine.solve", kernel=kernel, cold=True):
            with session.lock:
                p4t, t4p, price = session.solve()
                arena_stats = dict(session.arena.last_stats)
                # idempotence cache + warm checkpoint for tick 0: a
                # crash before the first delta must restore the session
                # (flush-before-ack, same as every delta tick)
                session.last_p4t = np.asarray(p4t, np.int32)
                if req.stream_mode:
                    # streaming session: bind the online engine to the
                    # just-primed arena — event-typed deltas route
                    # through per-event localized repair from here on
                    from protocol_tpu.stream.engine import StreamEngine

                    session.stream = StreamEngine(
                        session.arena, weights,
                        reconcile_every=(
                            int(req.reconcile_every) or 256
                        ),
                        gap_ceiling=_stream_gap_ceiling(),
                    )
                if self.ckpt is not None:
                    self.ckpt.flush_locked(session)
        # post-flush fence re-check (same freeze-window argument as the
        # delta path): an open that raced an ejection must not be acked
        # — the client re-opens at the new home instead of holding a
        # session whose journal can never exist here
        fenced = self._fence_route(session_id) if session_id else None
        if fenced is not None:
            if fenced:
                return pb.OpenSessionResponse(
                    ok=False, error=f"moved:{fenced}"
                )
            return pb.OpenSessionResponse(
                ok=False,
                error="unknown session (journal fence superseded)",
            )
        t_solve = time.perf_counter()
        self.sessions.put(session)
        self._router_adopt(session.session_id)
        self.seam.count("session_open")
        self.seam.observe_ms("decode", (t_dec - t0) * 1e3)
        self.seam.observe_ms("solve", (t_solve - t_dec) * 1e3)
        self._observe_tick(
            session.session_id, t0, session.n_tasks,
            int((p4t >= 0).sum()), arena_stats, trace_tick=0,
        )
        if self.trace is not None:
            # flight recorder, session mode: the snapshot frame is the
            # session's own wire message, deltas land from apply_delta
            # (one session claims the stream; later sessions are not
            # recorded — one trace, one session)
            try:
                if self.trace.record_session_open(
                    session.session_id, fp, req
                ):
                    session.trace = self.trace
                    self.trace.record_outcome(
                        0, p4t, price,
                        metrics=self._enrich_metrics({
                            "decode_ms": round((t_dec - t0) * 1e3, 3),
                            "solve_ms": round((t_solve - t_dec) * 1e3, 3),
                            "bytes_in": wire_bytes,
                            "wire": "v2-session",
                        }, arena_stats, mark, root),
                        session_id=session.session_id,
                    )
            except Exception:  # pragma: no cover - capture must not fail RPCs
                import logging

                logging.getLogger(__name__).warning(
                    "trace capture failed at OpenSession", exc_info=True
                )
        out = _SolveOut(p4t, t4p, int((p4t >= 0).sum()), price)
        resp = pb.OpenSessionResponse(
            ok=True,
            session_id=session.session_id,
            epoch_fingerprint=fp,
            result=self._result_v2(out, t0, t_dec - t0),
        )
        self.seam.add_bytes("out", resp.ByteSize())
        return resp

    def AssignDelta(
        self, request: pb.AssignDeltaRequest, context
    ) -> pb.AssignDeltaResponse:
        mark = _tracer.mark()
        with self._rpc_span(
            "rpc.AssignDelta", context,
            session=request.session_id,
            tick=int(request.tick),  # lint: unlocked-ok (wire message field, not session state)
        ) as root:
            return self._assign_delta(request, context, mark, root)

    def _assign_delta(
        self, request: pb.AssignDeltaRequest, context, mark: int, root
    ) -> pb.AssignDeltaResponse:
        t0 = time.perf_counter()
        # fence first (one stat call): an EJECTED process must refuse
        # every delta outright — before it consumes a tenant's
        # admission tokens or a store lookup — because its journal
        # namespace (and therefore the authority to ack) moved on
        fenced = self._fence_route(request.session_id)
        if fenced is not None:
            if fenced:
                return pb.AssignDeltaResponse(
                    session_ok=False, error=f"moved:{fenced}"
                )
            return pb.AssignDeltaResponse(
                session_ok=False,
                error="unknown session (journal fence superseded)",
            )
        # tenant admission next (cheapest stateful check): an over-rate
        # tenant is refused before it costs a store lookup or a decode
        if not self.admission.admit(tenant_of(request.session_id)):
            self.seam.count("admission_refused")
            return pb.AssignDeltaResponse(
                session_ok=False,
                error="RESOURCE_EXHAUSTED: tenant over admission rate "
                      "(AssignDelta)",
            )
        session, reason = self.sessions.get(
            request.session_id, request.epoch_fingerprint
        )
        if session is None and reason == "unknown session":
            # dfleet: a migrated-away session answers with its new home
            # (the client rebinds and resends the SAME delta — warm);
            # a session whose journal was handed TO us rehydrates here
            # lazily and the delta proceeds as if it never moved
            moved = self._moved_to(request.session_id)
            if moved is not None:
                self.seam.count("moved_refused")
                return pb.AssignDeltaResponse(
                    session_ok=False, error=f"moved:{moved}"
                )
            session = self._rehydrate(
                request.session_id, request.epoch_fingerprint
            )
        if session is None:
            self.seam.count("session_miss")
            return pb.AssignDeltaResponse(session_ok=False, error=reason)
        # delta-stream backpressure: the queued-tick depth bound must be
        # checked BEFORE parking on the session lock — over-depth means
        # this session is already stacked with waiting ticks, and
        # admitting one more would just grow the invisible lock queue
        if not session.enter_tick(self.fleet_config.delta_queue_depth):
            self.seam.count("backpressure_refused")
            return pb.AssignDeltaResponse(
                session_ok=False,
                error="RESOURCE_EXHAUSTED: session delta queue over "
                      f"depth {self.fleet_config.delta_queue_depth}",
            )
        try:
            return self._assign_delta_admitted(
                request, context, mark, root, t0, session
            )
        finally:
            session.exit_tick()

    def _assign_delta_admitted(
        self,
        request: pb.AssignDeltaRequest,
        context,
        mark: int,
        root,
        t0: float,
        session: SolveSession,
    ) -> pb.AssignDeltaResponse:
        self.seam.count("session_hit")
        self.seam.add_bytes("in", request.ByteSize())
        try:
            with _tracer.span("wire.decode", wire="v2-session"):
                prow = (
                    unblob(request.provider_rows, np.int32)
                    if request.HasField("provider_rows")
                    else np.zeros(0, np.int32)
                )
                trow = (
                    unblob(request.task_rows, np.int32)
                    if request.HasField("task_rows")
                    else np.zeros(0, np.int32)
                )
                p_delta = (
                    canon_columns(
                        decode_providers_v2(request.providers),
                        P_WIRE_DTYPES,
                    )
                    if prow.size else {}
                )
                r_delta = (
                    canon_columns(
                        decode_requirements_v2(request.requirements),
                        R_WIRE_DTYPES,
                    )
                    if trow.size else {}
                )
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        # decode ends HERE: with sharded session locks and a shared thread
        # budget, a delta can legitimately park on the lock — stamping
        # decode after it would misattribute contention to the codec and
        # point seam tuning at the wrong phase (lock/budget wait + delta
        # apply land in "solve" instead, where the contention actually is)
        t_dec = time.perf_counter()
        with _tracer.span(
            "engine.solve", kernel=session.kernel,
            delta_rows=int(prow.size + trow.size),
        ), session.lock:
            if session.evicted:
                # lost the race with LRU/TTL eviction (or a same-id
                # re-open) between the store lookup and this lock: refuse
                # rather than solve against — and advance the tick of — an
                # arena the store no longer owns. The client re-opens from
                # its authoritative state (the standard fallback ladder).
                self.seam.count("session_evicted_inflight")
                return pb.AssignDeltaResponse(
                    session_ok=False, error="session evicted"
                )
            if (
                int(request.tick) == session.tick
                and session.tick > 0
                and session.last_p4t is not None
            ):
                # idempotent retransmit: the client re-sent a tick this
                # session already applied — its response died on the
                # wire, or the servicer crashed after the
                # flush-before-ack checkpoint and the client retried
                # against the restart. The CRC proves it is the SAME
                # delta (byte-identical retransmit); the cached answer
                # replays and the tick is applied exactly once. A
                # same-tick request with DIFFERENT bytes is genuine
                # divergence and refuses below.
                if _delta_crc(request) == session.last_delta_crc:
                    self.seam.count("delta_replayed")
                    cached = np.asarray(session.last_p4t, np.int32)
                    return pb.AssignDeltaResponse(
                        session_ok=True,
                        replayed=True,
                        result=pb.AssignResponseV2(
                            provider_for_task=blob(cached, np.int32),
                            num_assigned=int((cached >= 0).sum()),
                            solve_ms=(time.perf_counter() - t0) * 1e3,
                        ),
                    )
            if int(request.tick) != session.tick + 1:
                # replayed or skipped tick: the client's shadow copy and
                # this session's columns have diverged — refuse, never
                # guess (the client re-opens from authoritative state)
                self.seam.count("tick_mismatch")
                return pb.AssignDeltaResponse(
                    session_ok=False,
                    error=f"tick cursor mismatch (have {session.tick}, "
                          f"got {int(request.tick)})",
                )
            # honor the caller's gRPC deadline/cancellation BEFORE the
            # delta is applied: an abort after apply_delta (but before
            # the tick cursor + dedup CRC advance) would let the
            # client's retry DOUBLE-APPLY this tick — the exact bug the
            # retransmit protocol exists to refuse
            self._check_deadline(context, "delta")
            is_event = bool(request.event_source)
            ev_deduped = ev_reconciled = False
            ev_gap = 0.0
            ev_window = 0
            if is_event and session.stream is None:
                # event-typed deltas need the stream engine the session
                # opted into at open; a batch session refuses with a
                # ladder-recognizable capability marker (the client
                # re-opens with stream_mode or stays on batch ticks)
                self.seam.count("stream_refused")
                return pb.AssignDeltaResponse(
                    session_ok=False,
                    error="not stream-servable (session opened without "
                          "stream_mode)",
                )
            if is_event and session.stream.stale_event(
                request.event_source, int(request.event_seq)
            ):
                # idempotence under chaos: a duplicated or reordered
                # (superseded) event is ACKED without applying — the
                # columns, arena, and plan are exactly as if it never
                # arrived, and the per-source high-water mark makes a
                # double-apply impossible by construction. The tick
                # cursor still advances (the wire stream consumed a
                # tick), which is what keeps the client's lockstep
                # cursor and the dedup CRC consistent.
                ev_deduped = True
                staleness = 0
                p4t_out = np.array(session.last_p4t, np.int32)
                ev_window = session.stream.events_since_reconcile
                # the served plan's HONEST certificate: the engine's
                # last computed bound, never a false 0.0 "optimal"
                ev_gap = float(session.stream.gap_last)
                price = None
                arena_stats = {
                    "cold": False, "event": True, "deduped": True,
                    "assigned": int((p4t_out >= 0).sum()),
                }
            else:
                try:
                    session.apply_delta(
                        prow, p_delta, trow, r_delta,
                        events=(
                            [{
                                "kind": request.event_kind or "event",
                                "source": request.event_source,
                                "seq": int(request.event_seq),
                            }]
                            if is_event else None
                        ),
                    )
                except ValueError as e:
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT, str(e)
                    )
            if is_event and not ev_deduped:
                from protocol_tpu.stream.events import StreamEvent

                res = session.stream.apply(StreamEvent(
                    kind=request.event_kind or "event",
                    source=request.event_source,
                    seq=int(request.event_seq),
                    provider_rows=prow,
                    p_cols=p_delta,
                    task_rows=trow,
                    r_cols=r_delta,
                ))
                staleness = 0
                p4t_out = np.asarray(res.plan, np.int32)[
                    : session.n_tasks
                ]
                price = None
                ev_reconciled = res.reconciled
                ev_gap = float(res.gap_per_task)
                ev_window = res.events_since_reconcile
                arena_stats = dict(session.arena.last_stats)
                arena_stats["stream_divergence_rows"] = (
                    res.divergence_rows
                )
                arena_stats["stream_repair_rows"] = res.repair_rows
                arena_stats["gap_per_task"] = ev_gap
                if res.stale:
                    # starved reconcile past the bound: flagged +
                    # counted, same contract as the tick watchdog
                    arena_stats["stale"] = True
                    arena_stats["stale_streak"] = (
                        res.events_since_reconcile
                        - session.stream.max_stale_events
                    )
            if not is_event:
                # ---- graceful degradation: the per-tick solve watchdog.
                # When the tick's deadline budget is already burned (lock
                # wait + decode + the EWMA of recent solve walls would
                # overrun it), serve the PREVIOUS plan with an explicit
                # stale flag instead of starting a solve whose answer will
                # arrive too late to act on. The delta was still APPLIED —
                # columns stay client-consistent — and the streak is
                # hard-bounded by ``max_stale_ticks``: past it the solve
                # runs regardless, so staleness is a contract, never an
                # escape hatch. (The native solve is uninterruptible C++;
                # the watchdog is predictive, which is the only honest kind
                # here.)
                deadline_ms = self.fleet_config.tick_deadline_ms
                stale = (
                    deadline_ms is not None
                    and session.last_p4t is not None
                    and session.stale_streak
                    < self.fleet_config.max_stale_ticks
                    and (time.perf_counter() - t0) * 1e3
                    + session.solve_ewma_ms > deadline_ms
                )
                if stale:
                    session.stale_streak += 1
                    staleness = session.stale_streak
                    p4t_out = np.array(session.last_p4t, np.int32)
                    price = None
                    arena_stats = {
                        "cold": False,  # served from carried state: a
                        # stale tick must not read as a cold solve in obs
                        "stale": True, "stale_streak": staleness,
                        "assigned": int((p4t_out >= 0).sum()),
                    }
                    self.seam.count("stale_served")
                else:
                    # (the deadline was already honored before apply_delta;
                    # re-checking here would abort AFTER state moved)
                    staleness = 0
                    t_s0 = time.perf_counter()
                    p4t_out, t4p, price = session.solve()
                    solve_ms = (time.perf_counter() - t_s0) * 1e3
                    # EWMA of solve walls feeds the watchdog's prediction
                    session.solve_ewma_ms = (
                        solve_ms if session.solve_ewma_ms == 0.0
                        else 0.5 * session.solve_ewma_ms + 0.5 * solve_ms
                    )
                    session.stale_streak = 0
                    arena_stats = dict(session.arena.last_stats)
                    del t4p  # derivable client-side; stays server-side
            session.tick += 1
            tick_no = session.tick  # this delta's wire tick, for the
            # post-lock obs/event hooks (== int(request.tick), checked
            # above)
            # idempotence cache: what a retransmit of THIS tick replays
            session.last_p4t = p4t_out
            session.last_delta_crc = _delta_crc(request)
            if session.evicted:
                # eviction landed DURING the solve (the store flags
                # without taking session.lock — coupling store eviction
                # to a potentially long solve would be worse): the solve
                # ran against a disowned arena, so do not ack it. The
                # pre-lock check above catches the common race; this one
                # closes the in-solve window.
                self.seam.count("session_evicted_inflight")
                return pb.AssignDeltaResponse(
                    session_ok=False, error="session evicted"
                )
            if session.trace is not None:
                from protocol_tpu.trace.recorder import safe as _trace_safe

                # outcome for the tick whose delta apply_delta recorded;
                # inside the lock so tick/outcome numbering can't race a
                # concurrent delta on the same session
                _trace_safe(
                    session.trace.record_outcome, session.tick, p4t_out,
                    price,
                    metrics=self._enrich_metrics({
                        "decode_ms": round((t_dec - t0) * 1e3, 3),
                        "solve_ms": round(
                            (time.perf_counter() - t_dec) * 1e3, 3
                        ),
                        "bytes_in": request.ByteSize(),
                        "delta_rows": int(prow.size + trow.size),
                        "wire": "v2-session",
                        **(
                            {"stale": True, "staleness_ticks": staleness}
                            if staleness else {}
                        ),
                    }, arena_stats, mark, root),
                    session_id=session.session_id,
                )
            if self.ckpt is not None and self.ckpt.due(session.tick):
                # flush-before-ack: the checkpoint lands on disk BEFORE
                # the client sees this tick acknowledged, so a crash at
                # any instant leaves the cursor at-or-one-behind the
                # client's — either the restart resumes at the next
                # tick, or the client's retransmit hits the dedup path.
                self.ckpt.flush_locked(session)
            # fence re-check AFTER the flush attempt, immediately
            # before the ack: a SIGSTOP can freeze this thread at ANY
            # instruction and the ejection (fence bump + journal
            # re-route) happen while it was frozen. Checking here —
            # after the flush, which itself refuses on a superseded
            # fence — closes every freeze window: whatever instant the
            # freeze hit, either the flushed journal traveled with the
            # re-route (the resend dedups as the replayed twin) or the
            # flush was fence-refused and this ack is WITHHELD (the
            # client resends at the new home, which holds the pre-tick
            # journal — applied exactly once, split-brain refused).
            fenced = self._fence_route(request.session_id)
            if fenced is not None:
                if fenced:
                    return pb.AssignDeltaResponse(
                        session_ok=False, error=f"moved:{fenced}"
                    )
                return pb.AssignDeltaResponse(
                    session_ok=False,
                    error="unknown session (journal fence superseded)",
                )
        self.seam.observe_ms("decode", (t_dec - t0) * 1e3)
        self.seam.observe_ms(
            "solve", (time.perf_counter() - t_dec) * 1e3
        )
        self._observe_tick(
            session.session_id, t0, session.n_tasks,
            int((p4t_out >= 0).sum()), arena_stats,
            delta_rows=int(prow.size + trow.size),
            trace_tick=tick_no,
        )
        if is_event and obs_pkg.enabled():
            # per-event stream metrics ride NEXT TO the tick roll-up:
            # event latency (µs-scale HDR), dedup/reconcile counters,
            # divergence + repair scope
            self.obs.observe_event(
                session.session_id,
                (time.perf_counter() - t0) * 1e3,
                deduped=ev_deduped,
                reconciled=ev_reconciled,
                divergence_rows=int(
                    arena_stats.get("stream_divergence_rows", 0)
                ),
                repair_rows=int(
                    arena_stats.get("stream_repair_rows", 0)
                ),
            )
        del price  # session state: stays server-side
        # SLIM response: p4t only. task_for_provider is derivable from it
        # (the client scatters), and prices/retirement are session state —
        # shipping them back every tick would spend O(P) wire bytes on
        # data the delta protocol exists to keep off the wire
        resp = pb.AssignDeltaResponse(
            session_ok=True,
            stale=bool(staleness),
            staleness_ticks=staleness,
            result=pb.AssignResponseV2(
                provider_for_task=blob(p4t_out, np.int32),
                num_assigned=int((p4t_out >= 0).sum()),
                solve_ms=(time.perf_counter() - t0) * 1e3,
                decode_ms=(t_dec - t0) * 1e3,
            ),
        )
        if is_event:
            resp.event_deduped = ev_deduped
            resp.reconciled = ev_reconciled
            resp.gap_per_task = ev_gap
            resp.events_since_reconcile = ev_window
        self.seam.add_bytes("out", resp.ByteSize())
        return resp

    def finish_drain(self) -> int:
        """The drain tail: flush every live session's checkpoint and the
        trace recorder's tail frames. Called AFTER the server stopped
        accepting RPCs (in-flight ticks have finished), so each session
        lock is uncontended. Returns the number of sessions flushed."""
        flushed = 0
        if self.ckpt is not None:
            for session in self.sessions.snapshot_sessions():
                with session.lock:
                    if not session.evicted and self.ckpt.flush_locked(
                        session
                    ):
                        flushed += 1
        if self.trace is not None:
            self.trace.close()
        return flushed

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        # deterministic fleet sweep: health probes are the periodic
        # traffic every deployment already has, so idle expired sessions
        # release their arena bytes here instead of waiting for the next
        # data-path touch (the fabric also sweeps under budget pressure)
        self.sessions.sweep()
        devices = jax.devices()
        resp = pb.HealthResponse(
            status="ok",
            platform=devices[0].platform if devices else "none",
            device_count=len(devices),
        )
        seam = dict(self.seam.snapshot())
        seam["sessions_active"] = float(len(self.sessions))
        seam["session_evictions"] = float(self.sessions.evictions)
        seam["session_expirations"] = float(self.sessions.expirations)
        seam["draining"] = 1.0 if self.draining else 0.0
        if self.ckpt is not None:
            seam["ckpt_flushes"] = float(self.ckpt.flushes)
            seam["ckpt_flush_failures"] = float(
                self.ckpt.flush_failures
            )
            seam["ckpt_handoffs"] = float(self.ckpt.handoffs)
            seam["ckpt_fence_epoch"] = float(self.ckpt.fence_epoch)
            seam["ckpt_fence_refusals"] = float(
                self.ckpt.fence_refusals
            )
            seam["ckpt_journals_skipped"] = float(
                self.ckpt.journals_skipped
            )
        with self._router_lock:
            seam["sessions_moved_out"] = float(len(self._moved))
        for name in sorted(seam):
            resp.seam_metrics.add(name=name, value=seam[name])
        return resp


def _handlers(servicer: SchedulerBackendServicer) -> grpc.GenericRpcHandler:
    return grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "Assign": grpc.unary_unary_rpc_method_handler(
                servicer.Assign,
                request_deserializer=pb.AssignRequest.FromString,
                response_serializer=pb.AssignResponse.SerializeToString,
            ),
            "AssignV2": grpc.unary_unary_rpc_method_handler(
                servicer.AssignV2,
                request_deserializer=pb.AssignRequestV2.FromString,
                response_serializer=pb.AssignResponseV2.SerializeToString,
            ),
            "OpenSession": grpc.stream_unary_rpc_method_handler(
                servicer.OpenSession,
                request_deserializer=pb.SnapshotChunk.FromString,
                response_serializer=pb.OpenSessionResponse.SerializeToString,
            ),
            "AssignDelta": grpc.unary_unary_rpc_method_handler(
                servicer.AssignDelta,
                request_deserializer=pb.AssignDeltaRequest.FromString,
                response_serializer=pb.AssignDeltaResponse.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                servicer.Health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthResponse.SerializeToString,
            ),
            "Migrate": grpc.unary_unary_rpc_method_handler(
                servicer.Migrate,
                request_deserializer=pb.MigrateRequest.FromString,
                response_serializer=pb.MigrateResponse.SerializeToString,
            ),
        },
    )


# Columnar batches scale with the population: ~60 B/provider means the
# 4 MB gRPC default tops out near 70k providers. 1 GiB covers the 1M-scale
# ladder with headroom for the v1 unary path; it is a cap, not an
# allocation. (v2 streams snapshots in bounded chunks, so only v1 and the
# per-tick delta messages ever approach it.)
MAX_MESSAGE_BYTES = 1 << 30
_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]


def drain(server: grpc.Server, grace_s: float = 5.0) -> int:
    """Graceful drain (the SIGTERM path): stop admitting OpenSession,
    stop taking new RPCs and let in-flight ticks finish (``grace_s``),
    then flush every session checkpoint and the trace tail. Returns the
    number of sessions flushed; after this the process can exit 0 and a
    restarted servicer rehydrates every session warm."""
    servicer = server.servicer
    servicer.draining = True
    server.stop(grace=grace_s).wait()
    if server.metrics is not None:
        server.metrics.stop()
    return servicer.finish_drain()


def serve(
    address: str = "127.0.0.1:50061",
    max_workers: int = 4,
    metrics_port: Optional[int] = None,
    max_sessions: int = 8,
    session_ttl_s: float = 900.0,
    fleet=None,
    slo=None,
    chaos=None,
) -> grpc.Server:
    """Start the backend server (non-blocking; call .wait_for_termination()).
    The servicer rides on the returned server as ``.servicer`` (tests and
    diagnostics reach the session store / seam metrics through it).

    ``fleet`` is a :class:`~protocol_tpu.fleet.FleetConfig` (shard
    count, arena byte budgets, admission rate, delta queue depth);
    None reads ``PROTOCOL_TPU_FLEET_*`` from the environment, and the
    defaults are transparent for single-session use.

    ``slo`` is an :class:`~protocol_tpu.obs.slo.SLOConfig` (per-tenant
    quality/latency objectives with multi-window burn-rate alerting);
    None reads ``PROTOCOL_TPU_SLO_*`` — all unset leaves the engine
    inert.

    ``metrics_port`` starts the consolidated observability scrape
    endpoint (``/metrics`` prometheus text merging SeamMetrics + the
    per-session obs registry + store/budget gauges; ``/metrics.json``
    the authoritative snapshot) on that port (0 = ephemeral; the bound
    endpoint rides on the server as ``.metrics`` with its ``.port``).
    ``PROTOCOL_TPU_METRICS_PORT`` enables it from the environment. None
    and no env var: no HTTP listener (the Health RPC still serves the
    seam snapshot).

    ``chaos`` arms the server-side fault interceptor (drop/delay before
    the servicer) — a :class:`~protocol_tpu.faults.plan.ChaosConfig` or
    ``FaultSchedule``; None reads ``PROTOCOL_TPU_CHAOS`` from the
    environment (unset = no interceptor, zero overhead)."""
    interceptors: tuple = ()
    if chaos is None:
        from protocol_tpu.faults.plan import ChaosConfig

        chaos = ChaosConfig.from_env()
    if chaos is not None:
        from protocol_tpu.faults.inject import ChaosServerInterceptor
        from protocol_tpu.faults.plan import ChaosConfig, FaultSchedule

        schedule = (
            chaos if isinstance(chaos, FaultSchedule)
            else FaultSchedule(chaos)
        )
        if schedule.config.active():
            # the interceptor needs this process's identity so the
            # slow-node gray failure (slow_proc=K) can target ONE fleet
            # process while the rest stay fast
            proc_id = (
                fleet.proc_id if fleet is not None
                else os.environ.get("PROTOCOL_TPU_FLEET_PROC_ID", "p0")
            )
            interceptors = (
                ChaosServerInterceptor(schedule, proc_id=proc_id),
            )
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
        interceptors=interceptors,
    )
    servicer = SchedulerBackendServicer(
        max_sessions=max_sessions,
        session_ttl_s=session_ttl_s,
        fleet=fleet,
        slo=slo,
    )
    server.add_generic_rpc_handlers((_handlers(servicer),))
    server.servicer = servicer
    server.add_insecure_port(address)
    if metrics_port is None and os.environ.get("PROTOCOL_TPU_METRICS_PORT"):
        metrics_port = int(os.environ["PROTOCOL_TPU_METRICS_PORT"])
    server.metrics = None
    if metrics_port is not None:
        from protocol_tpu.obs.endpoint import start_for_servicer

        server.metrics = start_for_servicer(servicer, port=metrics_port)
    server.start()
    return server


class SchedulerBackendClient:
    """Thin client stub (what a non-Python control plane would generate)."""

    def __init__(self, address: str = "127.0.0.1:50061"):
        self.address = address
        self.channel = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
        self._assign = self.channel.unary_unary(
            f"/{SERVICE_NAME}/Assign",
            request_serializer=pb.AssignRequest.SerializeToString,
            response_deserializer=pb.AssignResponse.FromString,
        )
        self._assign_v2 = self.channel.unary_unary(
            f"/{SERVICE_NAME}/AssignV2",
            request_serializer=pb.AssignRequestV2.SerializeToString,
            response_deserializer=pb.AssignResponseV2.FromString,
        )
        self._open_session = self.channel.stream_unary(
            f"/{SERVICE_NAME}/OpenSession",
            request_serializer=pb.SnapshotChunk.SerializeToString,
            response_deserializer=pb.OpenSessionResponse.FromString,
        )
        self._assign_delta = self.channel.unary_unary(
            f"/{SERVICE_NAME}/AssignDelta",
            request_serializer=pb.AssignDeltaRequest.SerializeToString,
            response_deserializer=pb.AssignDeltaResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE_NAME}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )
        self._migrate = self.channel.unary_unary(
            f"/{SERVICE_NAME}/Migrate",
            request_serializer=pb.MigrateRequest.SerializeToString,
            response_deserializer=pb.MigrateResponse.FromString,
        )

    @staticmethod
    def _md(metadata):
        """Outgoing metadata with the caller's span context injected
        (``x-pt-span``), so the servicer's RPC spans stitch into the
        client tick's trace. No open span / tracing off: pass-through."""
        return _tracer.inject(metadata)

    def assign(
        self, request: pb.AssignRequest, timeout: float = 60.0,
        metadata=None,
    ) -> pb.AssignResponse:
        return self._assign(
            request, timeout=timeout, metadata=self._md(metadata)
        )

    def assign_v2(
        self, request: pb.AssignRequestV2, timeout: float = 60.0,
        metadata=None,
    ) -> pb.AssignResponseV2:
        return self._assign_v2(
            request, timeout=timeout, metadata=self._md(metadata)
        )

    def open_session(
        self, chunks, timeout: float = 300.0, metadata=None
    ) -> pb.OpenSessionResponse:
        return self._open_session(
            chunks, timeout=timeout, metadata=self._md(metadata)
        )

    def assign_delta(
        self, request: pb.AssignDeltaRequest, timeout: float = 60.0,
        metadata=None,
    ) -> pb.AssignDeltaResponse:
        return self._assign_delta(
            request, timeout=timeout, metadata=self._md(metadata)
        )

    def health(self, timeout: float = 10.0) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=timeout)

    def migrate(
        self, request: pb.MigrateRequest, timeout: float = 120.0,
    ) -> pb.MigrateResponse:
        return self._migrate(request, timeout=timeout)

    def close(self) -> None:
        self.channel.close()


def encoded_to_proto(
    ep: EncodedProviders, er: EncodedRequirements, weights: Optional[CostWeights] = None,
    kernel: str = "topk", top_k: int = 64, eps: float = 0.01, max_iters: int = 0,
) -> pb.AssignRequest:
    """Host-side helper: pack numpy-backed encodings into an AssignRequest.

    Columns go to protobuf as numpy arrays directly (upb consumes any
    iterable of scalars): dtypes are asserted/narrowed ONCE here via an
    ascontiguousarray cast, and the per-element Python list round-trip the
    old ``.tolist()`` spelling paid on every column is gone."""

    def _c(a, dtype):
        return np.ascontiguousarray(np.asarray(a), dtype)

    w = weights or CostWeights()
    t, k = np.asarray(er.gpu_opt_valid).shape
    words = np.asarray(er.gpu_model_mask).shape[-1]
    return pb.AssignRequest(
        providers=pb.ProviderBatch(
            gpu_count=_c(ep.gpu_count, np.int32),
            gpu_mem_mb=_c(ep.gpu_mem_mb, np.int32),
            gpu_model_id=_c(ep.gpu_model_id, np.int32),
            has_gpu=_c(ep.has_gpu, bool),
            has_cpu=_c(ep.has_cpu, bool),
            cpu_cores=_c(ep.cpu_cores, np.int32),
            ram_mb=_c(ep.ram_mb, np.int32),
            storage_gb=_c(ep.storage_gb, np.int32),
            lat=_c(ep.lat, np.float32),
            lon=_c(ep.lon, np.float32),
            has_location=_c(ep.has_location, bool),
            price=_c(ep.price, np.float32),
            load=_c(ep.load, np.float32),
        ),
        requirements=pb.RequirementBatch(
            cpu_required=_c(er.cpu_required, bool),
            cpu_cores=_c(er.cpu_cores, np.int32),
            ram_mb=_c(er.ram_mb, np.int32),
            storage_gb=_c(er.storage_gb, np.int32),
            max_gpu_options=k,
            model_words=words,
            gpu_opt_valid=_c(er.gpu_opt_valid, bool).reshape(-1),
            gpu_count=_c(er.gpu_count, np.int32).reshape(-1),
            gpu_mem_min=_c(er.gpu_mem_min, np.int32).reshape(-1),
            gpu_mem_max=_c(er.gpu_mem_max, np.int32).reshape(-1),
            gpu_total_mem_min=_c(er.gpu_total_mem_min, np.int32).reshape(-1),
            gpu_total_mem_max=_c(er.gpu_total_mem_max, np.int32).reshape(-1),
            gpu_model_mask=_c(er.gpu_model_mask, np.uint32).reshape(-1),
            gpu_model_constrained=_c(er.gpu_model_constrained, bool).reshape(-1),
            lat=_c(er.lat, np.float32),
            lon=_c(er.lon, np.float32),
            has_location=_c(er.has_location, bool),
            priority=_c(er.priority, np.float32),
        ),
        weights=pb.CostWeights(
            price=float(w.price), load=float(w.load),
            proximity=float(w.proximity), priority=float(w.priority),
        ),
        kernel=kernel,
        top_k=top_k,
        eps=eps,
        max_iters=max_iters,
    )


def encoded_to_proto_v2(
    ep: EncodedProviders, er: EncodedRequirements,
    weights: Optional[CostWeights] = None,
    kernel: str = "topk", top_k: int = 64, eps: float = 0.01,
    max_iters: int = 0,
) -> pb.AssignRequestV2:
    """v2 twin of :func:`encoded_to_proto`: tensor-frame columns."""
    w = weights or CostWeights()
    return pb.AssignRequestV2(
        providers=encode_providers_v2(ep),
        requirements=encode_requirements_v2(er),
        weights=pb.CostWeights(
            price=float(w.price), load=float(w.load),
            proximity=float(w.proximity), priority=float(w.priority),
        ),
        kernel=kernel,
        top_k=top_k,
        eps=eps,
        max_iters=max_iters,
    )


class _WireResult(NamedTuple):
    """Version-independent view of an assign response."""

    p4t: np.ndarray
    t4p: np.ndarray
    price: Optional[np.ndarray]
    solve_ms: float


def _res_v1(resp: pb.AssignResponse) -> _WireResult:
    return _WireResult(
        _np(resp.provider_for_task, np.int32),
        _np(resp.task_for_provider, np.int32),
        _np(resp.price, np.float32) if len(resp.price) else None,
        resp.solve_ms,
    )


def _res_v2(
    resp: pb.AssignResponseV2, n_providers: Optional[int] = None
) -> _WireResult:
    p4t = unblob(resp.provider_for_task, np.int32)
    if resp.HasField("task_for_provider"):
        t4p = unblob(resp.task_for_provider, np.int32)
    else:
        # slim delta response: the inverse matching is a local scatter
        t4p = np.full(int(n_providers), -1, np.int32)
        seated = np.flatnonzero((p4t >= 0) & (p4t < int(n_providers)))
        t4p[p4t[seated]] = seated.astype(np.int32)
    return _WireResult(
        p4t,
        t4p,
        unblob(resp.price, np.float32)
        if resp.HasField("price") else None,
        resp.solve_ms,
    )


_RETRYABLE = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)

# OpenSession refusal markers that are CAPABILITY answers (the server
# will never serve this session protocol for these parameters): only
# these may demote the client's ladder permanently. Anything else —
# torn streams, draining servers, corrupted frames — is transient.
_PERMANENT_REFUSALS = (
    "not session-servable",
    "fingerprint mismatch",
)


class RemoteBatchMatcher(TpuBatchMatcher):
    """TpuBatchMatcher whose device solves go through the gRPC scheduler
    backend (``scheduler_backend=remote``): the control plane stays a thin
    host process while the kernels run wherever the backend's accelerator
    lives. This is the load-bearing form of the BASELINE.json north-star
    seam — the same columnar batches the in-process matcher feeds its
    jitted kernels are packed into AssignRequests instead, so control
    plane and backend can be scaled and deployed independently (the
    reference's Rust-orchestrator-calls-TPU-service shape).

    ``wire="v1"`` speaks the frozen repeated-scalar contract.
    ``wire="v2"`` speaks tensor frames, and for the native-mt engine runs
    the session protocol: one streamed snapshot, then per-tick
    ``AssignDelta`` messages carrying only rows whose encoded values
    changed since the previous solve (a vectorized column diff against
    the client's shadow copy — the wire twin of the CandidateCache /
    arena dirty-row bookkeeping). A refused delta re-opens the session
    from a fresh snapshot; an UNIMPLEMENTED v2 RPC (old server) drops the
    client to v1 permanently. Transient transport failures
    (UNAVAILABLE / DEADLINE_EXCEEDED) retry with bounded exponential
    backoff and a channel reconnect — one flaky RPC must not fail a
    whole scheduler tick.

    Round-trip cost shows up in ``last_solve_stats`` as
    ``remote_rtt_ms`` (client-observed) next to the backend-reported
    ``solve_ms`` per call; the difference is the columnar seam's cost
    (SURVEY.md §7 hard part #6 wants it cheap — measured, not asserted).
    """

    # candidates are generated behind the seam; the in-process candidate
    # cache cannot hold them (warm prices still ride the wire)
    use_candidate_cache = False

    def attach_groups(self, plugin) -> None:
        # The group solve is tiny (groups x tasks) and runs in-process even
        # on the remote matcher — but this control-plane host must never
        # lazily initialize a remote accelerator platform (a wedged tunnel
        # would hang the solve path). Pin jax to the host CPU first; every
        # LARGE solve still rides the gRPC seam.
        import jax

        jax.config.update("jax_platforms", "cpu")
        super().attach_groups(plugin)

    def __init__(
        self,
        store,
        address="127.0.0.1:50061",
        request_timeout: float = 300.0,
        wire: str = "v1",
        chunk_bytes: int = 1 << 20,
        gzip_snapshots: bool = True,
        retries: int = 3,
        retry_base_s: float = 0.05,
        retry_max_s: float = 2.0,
        tick_timeout_s: Optional[float] = None,
        **kwargs,
    ):
        super().__init__(store, **kwargs)
        if wire not in ("v1", "v2"):
            raise ValueError(f"wire must be v1|v2, got {wire!r}")
        # ``address`` accepts one endpoint, a comma-separated list, or a
        # sequence: an ORDERED endpoint list is the dfleet failover
        # ladder — transport failures past the first reconnect rotate
        # to the next endpoint, and a "moved:<endpoint>" refusal
        # rebinds directly (see rebind()).
        if isinstance(address, (list, tuple)):
            endpoints = [str(a) for a in address]
        else:
            endpoints = [a.strip() for a in str(address).split(",")]
        self.endpoints = [e for e in endpoints if e] or [
            "127.0.0.1:50061"
        ]
        self._endpoint_i = 0
        self.request_timeout = request_timeout
        # per-RPC deadline sized to the tick budget: steady-state solve
        # RPCs (unary + AssignDelta) carry this deadline so a wedged
        # server fails THIS tick fast instead of parking the scheduler
        # loop for request_timeout; the cold OpenSession stream keeps
        # the long timeout (a snapshot solve legitimately takes it).
        # None = no tick budget (fall back to request_timeout).
        self.tick_timeout_s = tick_timeout_s
        self.wire = wire
        self.chunk_bytes = chunk_bytes
        self.gzip_snapshots = gzip_snapshots
        self.retries = retries
        self.retry_base_s = retry_base_s
        self.retry_max_s = retry_max_s
        self.client = SchedulerBackendClient(self.endpoints[0])
        # generation-monotonic topology adoption (dfleet): the highest
        # FleetTopology generation this client ever adopted — a stale
        # /fleet.json poll racing a detector ejection must LOSE
        self._topology_generation: Optional[int] = None
        self.seam = SeamMetrics(role="client")
        self._rtt_ms: list[float] = []
        self._backend_ms: list[float] = []
        self._bytes_out = 0
        self._bytes_in = 0
        # client half of the session protocol: shadow columns of the last
        # snapshot/delta the server acknowledged, keyed by solve params
        self._session: Optional[dict] = None
        self._session_uid = uuid.uuid4().hex
        self._session_refused = False
        # resilience counters for the current refresh (degraded answers
        # are explicit all the way up: the matcher's stats name them)
        self._stale_ticks = 0
        self._replayed_ticks = 0

    def refresh(self) -> None:
        self._rtt_ms, self._backend_ms = [], []
        self._bytes_out = self._bytes_in = 0
        self._stale_ticks = self._replayed_ticks = 0
        # one causal trace per scheduler tick: every RPC this refresh
        # issues injects this span's context, and the servicer's spans
        # adopt it — "where did the tick go" is answerable end to end
        with _tracer.span("seam.tick", wire=self.wire):
            super().refresh()  # replaces last_solve_stats; re-attach remote cost
        if self._rtt_ms:
            self.last_solve_stats["wire"] = self.wire
            self.last_solve_stats["remote_calls"] = len(self._rtt_ms)
            self.last_solve_stats["remote_rtt_ms"] = round(sum(self._rtt_ms), 3)
            self.last_solve_stats["remote_backend_ms"] = round(
                sum(self._backend_ms), 3
            )
            self.last_solve_stats["remote_bytes_out"] = self._bytes_out
            self.last_solve_stats["remote_bytes_in"] = self._bytes_in
            if self._stale_ticks:
                self.last_solve_stats["stale_ticks"] = self._stale_ticks
            if self._replayed_ticks:
                self.last_solve_stats["replayed_ticks"] = (
                    self._replayed_ticks
                )

    @staticmethod
    def _strip_padding(enc):
        return strip_padding(enc)

    # ---------------- transport: retry + reconnect ----------------

    def rebind(self, endpoint: Optional[str] = None) -> None:
        """Reconnect the channel — to ``endpoint`` when given (a
        "moved:<endpoint>" migration redirect, inserted into the
        failover list if new), else to the current endpoint. A chaos
        shim (faults.inject.ChaosClient) keeps its injector and fault
        cursors: only the dead channel under it is swapped."""
        if endpoint:
            if endpoint not in self.endpoints:
                self.endpoints.append(endpoint)
            self._endpoint_i = self.endpoints.index(endpoint)
        fresh = SchedulerBackendClient(self.endpoints[self._endpoint_i])
        shim_rebind = getattr(self.client, "rebind", None)
        if callable(shim_rebind):
            shim_rebind(fresh)
            return
        try:
            self.client.close()
        except Exception:
            pass
        self.client = fresh

    def adopt_topology(self, topology, session_id=None) -> bool:
        """Adopt a fleet topology (a discovery poll / manager push):
        the failover endpoint list becomes the ring's ordered walk for
        this client's session. GENERATION-MONOTONIC: a topology no
        newer than the one already adopted is refused (returns False,
        counted) — a stale ``/fleet.json`` poll racing a detector
        ejection must never resurrect an ejected endpoint into the
        ladder. If the currently-bound endpoint was ejected, the
        channel rebinds to the new home immediately."""
        gen = int(getattr(topology, "generation", 0))
        if (
            self._topology_generation is not None
            and gen <= self._topology_generation
        ):
            self.seam.count("stale_topology_refused")
            return False
        self._topology_generation = gen
        sid = session_id or (
            (self._session or {}).get("id") or self._session_uid
        )
        current = self.endpoints[self._endpoint_i]
        self.endpoints = list(topology.failover_order(sid))
        if current in self.endpoints:
            self._endpoint_i = self.endpoints.index(current)
        else:
            # our endpoint was ejected from the ring: fail over now
            self._endpoint_i = 0
            self.seam.count("endpoint_failover")
            self.rebind()
        self.seam.count("topology_adopted")
        return True

    def _reconnect(self, failover: bool = False) -> None:
        """Fresh channel; with ``failover`` (a retry that already
        reconnected once and failed again) rotate to the next endpoint
        in the ordered list — a dead process's clients spread over the
        survivors instead of hammering the corpse."""
        if failover and len(self.endpoints) > 1:
            self._endpoint_i = (
                self._endpoint_i + 1
            ) % len(self.endpoints)
            self.seam.count("endpoint_failover")
        self.rebind()

    def _backoff_s(self, attempt: int) -> float:
        """Bounded exponential backoff with deterministic jitter for
        retry ``attempt`` (0-based): ``retry_base_s * 2^attempt`` capped
        at ``retry_max_s``, scaled into [0.5x, 1.5x) by a hash of this
        client's session uid + the attempt number. H clients restarting
        against a recovered server therefore spread their retries over
        the backoff window instead of thundering-herding it in lockstep
        — and the schedule is a pure function of (uid, attempt), so
        tests replay it exactly (no ``random``: the determinism lint's
        spirit holds even off the kernel paths)."""
        base = min(self.retry_base_s * (2.0 ** attempt), self.retry_max_s)
        import hashlib

        digest = hashlib.sha1(
            f"{self._session_uid}:{attempt}".encode()
        ).digest()
        frac = int.from_bytes(digest[:8], "big") / 2.0 ** 64
        return min(base * (0.5 + frac), self.retry_max_s)

    def _rpc(self, make_call):
        """Run ``make_call()`` (a zero-arg closure issuing one RPC) with
        bounded, jittered exponential backoff on transient transport
        failures (see :meth:`_backoff_s`); each retry reconnects the
        channel (a dead server that came back gets a fresh HTTP/2
        connection instead of a wedged one). A RESOURCE_EXHAUSTED abort
        (the fleet's unary admission gate) backs off the same way but
        WITHOUT reconnecting — the server is healthy, its token bucket
        is just empty, and the refill is what the wait buys. Sustained
        throttle past the retry budget surfaces as the explicit error
        it is."""
        for attempt in range(self.retries + 1):
            try:
                return make_call()
            except grpc.RpcError as e:
                code = e.code()
                if attempt >= self.retries:
                    raise
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    self.seam.count("throttled_retry")
                    time.sleep(self._backoff_s(attempt))
                    continue
                if code not in _RETRYABLE:
                    raise
                self.seam.count("retry")
                time.sleep(self._backoff_s(attempt))
                # first retry reconnects the SAME endpoint (transient
                # blip); later retries fail over down the endpoint list
                self._reconnect(failover=attempt >= 1)

    # ---------------- v1/v2 unary ----------------

    def _timed(self, make_call, bytes_out: int):
        t0 = time.perf_counter()
        with _tracer.span("seam.rpc", wire=self.wire):
            resp = self._rpc(make_call)
        self._rtt_ms.append((time.perf_counter() - t0) * 1e3)
        self._bytes_out += bytes_out
        self._bytes_in += resp.ByteSize()
        return resp

    def _call(
        self, ep, er, kernel: str, eps: float, max_iters: int,
        warm_price=None, seed_p4t=None, top_k: int = 64,
    ) -> _WireResult:
        sp = self._strip_padding(ep)
        sr = self._strip_padding(er)
        if self.wire == "v2":
            try:
                return self._call_v2(
                    sp, sr, kernel, eps, max_iters, warm_price, seed_p4t,
                    top_k,
                )
            except grpc.RpcError as e:
                if e.code() != grpc.StatusCode.UNIMPLEMENTED:
                    raise
                # old server: drop to the frozen v1 contract for good
                self.wire = "v1"
                self.seam.count("fallback_v1")
        t0 = time.perf_counter()
        req = encoded_to_proto(
            sp, sr, self.weights,
            kernel=kernel, top_k=top_k, eps=eps, max_iters=max_iters,
        )
        if warm_price is not None and seed_p4t is not None:
            req.warm_price.extend(np.asarray(warm_price, np.float32))
            req.seed_provider_for_task.extend(
                np.asarray(seed_p4t, np.int32)
            )
        _t_ser = time.perf_counter()
        _tracer.record_span(
            "wire.encode", int(t0 * 1e9), int((_t_ser - t0) * 1e9),
            wire=self.wire,
        )
        self.seam.observe_ms("serialize", (_t_ser - t0) * 1e3)
        resp = self._timed(
            lambda: self.client.assign(req, timeout=self.request_timeout),
            req.ByteSize(),
        )
        self._backend_ms.append(resp.solve_ms)
        return _res_v1(resp)

    def _call_v2(
        self, sp, sr, kernel, eps, max_iters, warm_price, seed_p4t, top_k,
    ) -> _WireResult:
        if (
            parse_native_threads(kernel) is not None
            and not self._session_refused
        ):
            res = self._session_call(sp, sr, kernel, eps, max_iters, top_k)
            if res is not None:
                return res
        t0 = time.perf_counter()
        req = encoded_to_proto_v2(
            sp, sr, self.weights,
            kernel=kernel, top_k=top_k, eps=eps, max_iters=max_iters,
        )
        if warm_price is not None and seed_p4t is not None:
            req.warm_price.CopyFrom(blob(warm_price, np.float32))
            req.seed_provider_for_task.CopyFrom(blob(seed_p4t, np.int32))
        _t_ser = time.perf_counter()
        _tracer.record_span(
            "wire.encode", int(t0 * 1e9), int((_t_ser - t0) * 1e9),
            wire=self.wire,
        )
        self.seam.observe_ms("serialize", (_t_ser - t0) * 1e3)
        resp = self._timed(
            lambda: self.client.assign_v2(req, timeout=self.request_timeout),
            req.ByteSize(),
        )
        self._backend_ms.append(resp.solve_ms)
        return _res_v2(resp)

    # ---------------- v2 session protocol (client half) ----------------

    def _session_call(
        self, sp, sr, kernel, eps, max_iters, top_k,
    ) -> Optional[_WireResult]:
        """Session-protocol solve: delta tick against the open session, or
        a fresh streamed snapshot when there is none / the population
        reshaped / the server lost it. Returns None when the server
        refuses the session protocol (caller falls to unary v2)."""
        t0 = time.perf_counter()
        p_cols = canon_columns(sp, P_WIRE_DTYPES)
        r_cols = canon_columns(sr, R_WIRE_DTYPES)
        params = (
            kernel, int(top_k), float(eps), int(max_iters),
            float(self.weights.price), float(self.weights.load),
            float(self.weights.proximity), float(self.weights.priority),
            p_cols["gpu_count"].shape[0], r_cols["cpu_cores"].shape[0],
        )
        st = self._session
        if st is None or st["params"] != params:
            return self._open_session(
                p_cols, r_cols, kernel, eps, max_iters, top_k, params, t0
            )
        prow = dirty_rows(p_cols, st["p_cols"])
        trow = dirty_rows(r_cols, st["r_cols"])
        n_total = params[-2] + params[-1]
        if (prow.size + trow.size) > 0.5 * n_total:
            # a mostly-new marketplace: the delta message would carry more
            # than a snapshot's worth of rows — re-epoch instead
            return self._open_session(
                p_cols, r_cols, kernel, eps, max_iters, top_k, params, t0
            )
        req = pb.AssignDeltaRequest(
            session_id=st["id"],
            epoch_fingerprint=st["fp"],
            tick=st["tick"] + 1,
        )
        if prow.size:
            req.provider_rows.CopyFrom(blob(prow, np.int32))
            req.providers.CopyFrom(
                encode_providers_v2(take_rows(p_cols, prow))
            )
        if trow.size:
            req.task_rows.CopyFrom(blob(trow, np.int32))
            req.requirements.CopyFrom(
                encode_requirements_v2(take_rows(r_cols, trow))
            )
        _t_ser = time.perf_counter()
        _tracer.record_span(
            "wire.encode", int(t0 * 1e9), int((_t_ser - t0) * 1e9),
            wire=self.wire,
        )
        self.seam.observe_ms("serialize", (_t_ser - t0) * 1e3)
        # delta RPCs carry the TICK deadline (the budget this answer is
        # useful within), not the long snapshot timeout
        tick_timeout = self.tick_timeout_s or self.request_timeout
        try:
            resp = self._timed(
                lambda: self.client.assign_delta(
                    req, timeout=tick_timeout
                ),
                req.ByteSize(),
            )
        except grpc.RpcError as e:
            if e.code() != grpc.StatusCode.INVALID_ARGUMENT:
                raise
            # the frame was mangled in transit (the hardening layer
            # refused it at decode, BEFORE any session state moved):
            # resending the same delta is safe — once. A persistent
            # INVALID_ARGUMENT is a real contract violation and raises.
            self.seam.count("corrupt_resend")
            resp = self._timed(
                lambda: self.client.assign_delta(
                    req, timeout=tick_timeout
                ),
                req.ByteSize(),
            )
        if not resp.session_ok:
            resp = self._delta_refusal_ladder(resp, req, tick_timeout)
        if not resp.session_ok:
            # evicted / expired / served by a replica that never saw the
            # snapshot (or still throttled after the bounded retries):
            # re-open from our authoritative state, don't error the
            # scheduler tick
            self.seam.count("session_reopen")
            self._session = None
            return self._open_session(
                p_cols, r_cols, kernel, eps, max_iters, top_k, params, t0
            )
        st["p_cols"], st["r_cols"] = p_cols, r_cols
        st["tick"] += 1
        if resp.stale:
            # DEGRADED answer: the server burned its tick deadline and
            # served the previous plan, explicitly flagged. The delta
            # was still applied server-side (shadow update above is
            # correct); the staleness is bounded by the server's
            # max_stale_ticks contract and surfaced in solve stats.
            self.seam.count("stale_served")
            self._stale_ticks += 1
        if resp.replayed:
            # idempotent retransmit answer (our original send was
            # answered but the response died): counted, not an error
            self.seam.count("delta_replayed")
            self._replayed_ticks += 1
        self._backend_ms.append(resp.result.solve_ms)
        return _res_v2(resp.result, n_providers=params[-2])

    def _delta_refusal_ladder(self, resp, req, tick_timeout):
        """Refusal handling for one delta, each rung bounded; returns
        the final response (still not session_ok => the caller
        re-opens, the pre-dfleet last resort).

        throttle   RESOURCE_EXHAUSTED: admission/backpressure — retry
                   the SAME delta after jittered backoff (re-opening
                   would AMPLIFY an over-rate tenant's load into full
                   snapshot solves, the opposite of what the refusal
                   asked for).
        moved      "moved:<endpoint>": live migration redirect — rebind
                   to the new home and resend the SAME delta; the
                   session rehydrates warm there from its handed-off
                   journal (zero reopens is the whole point).
        evicted    one same-delta resend: a migration races an
                   in-flight delta as "session evicted"; the resend is
                   answered "moved:" (follow it warm) — a GENUINE
                   eviction answers "unknown session" and re-opens.
        handoff    "unknown session" with >1 endpoint: the journal
                   rename may still be in flight after a failover —
                   or a double transport blip failed us over AWAY from
                   the session's live home. Bounded backoff, rotating
                   an endpoint per wait (the owner — live session or
                   re-routed journal — is somewhere in the list), then
                   concede to a reopen.
        """
        throttles = redirects = waits = 0
        evict_retried = False
        # snapshot the redirect budget BEFORE the loop: rebind() grows
        # self.endpoints with each fresh redirect target, so a bound
        # read inside the loop would chase a split-brain map forever
        redirect_limit = len(self.endpoints) + 1
        while not resp.session_ok:
            err = resp.error
            if "RESOURCE_EXHAUSTED" in err:
                if throttles >= self.retries:
                    break
                self.seam.count("throttled_retry")
                time.sleep(self._backoff_s(throttles))
                throttles += 1
            elif err.startswith("moved:"):
                if redirects >= redirect_limit:
                    break  # redirect loop (split-brain maps): re-open
                self.seam.count("moved_redirect")
                self.rebind(err[len("moved:"):].strip())
                redirects += 1
            elif "session evicted" in err and not evict_retried:
                evict_retried = True
            elif "unknown session" in err and len(self.endpoints) > 1:
                if waits >= max(self.retries, len(self.endpoints)):
                    break
                self.seam.count("handoff_wait")
                time.sleep(self._backoff_s(waits))
                waits += 1
                self._reconnect(failover=True)
            else:
                break
            resp = self._timed(
                lambda: self.client.assign_delta(
                    req, timeout=tick_timeout
                ),
                req.ByteSize(),
            )
        return resp

    def _open_session(
        self, p_cols, r_cols, kernel, eps, max_iters, top_k, params, t0,
    ) -> Optional[_WireResult]:
        fp = epoch_fingerprint(
            p_cols, r_cols, self.weights, kernel, int(top_k), eps,
            int(max_iters),
        )
        req = encoded_to_proto_v2(
            take_rows(p_cols, slice(None)), take_rows(r_cols, slice(None)),
            self.weights, kernel=kernel, top_k=top_k, eps=eps,
            max_iters=max_iters,
        )
        chunks = list(
            chunk_snapshot(
                self._session_uid, fp, req,
                chunk_bytes=self.chunk_bytes,
                use_gzip=self.gzip_snapshots,
            )
        )
        n_bytes = sum(len(c.payload) for c in chunks)
        _t_ser = time.perf_counter()
        _tracer.record_span(
            "wire.encode", int(t0 * 1e9), int((_t_ser - t0) * 1e9),
            wire=self.wire,
        )
        self.seam.observe_ms("serialize", (_t_ser - t0) * 1e3)
        resp = self._timed(
            lambda: self.client.open_session(
                iter(chunks), timeout=self.request_timeout
            ),
            n_bytes,
        )
        redirects = 0
        redirect_limit = len(self.endpoints) + 1  # pre-loop snapshot:
        # rebind() appends fresh targets, a live bound never trips
        while (
            not resp.ok
            and resp.error.startswith("moved:")
            and redirects < redirect_limit
        ):
            # live-migration redirect on the OPEN itself: the session's
            # journal lives at the new home — opening here would fork
            # ownership, so the server bounced us there instead
            self.seam.count("moved_redirect")
            self.rebind(resp.error[len("moved:"):].strip())
            redirects += 1
            resp = self._timed(
                lambda: self.client.open_session(
                    iter(chunks), timeout=self.request_timeout
                ),
                n_bytes,
            )
        if not resp.ok:
            if "RESOURCE_EXHAUSTED" in resp.error:
                # admission throttle, NOT a capability refusal: this
                # tick degrades to the unary rung, but the session
                # protocol stays available — setting _session_refused
                # here would demote a briefly-throttled tenant to
                # unthrottled full-snapshot unary solves FOREVER
                self.seam.count("session_throttled")
                self._session = None
                return None
            if not any(
                marker in resp.error for marker in _PERMANENT_REFUSALS
            ):
                # transient refusal (torn/truncated snapshot stream, a
                # draining server, a corrupted frame the hardening
                # layer bounced): degrade THIS tick to unary and try
                # the session protocol again next tick — only a
                # capability answer may demote the ladder permanently
                self.seam.count("session_transient_refusal")
                self._session = None
                return None
            # server-side capability refusal is a protocol answer, not
            # a transport failure: remember it so every later tick goes
            # straight to the unary rung
            self.seam.count("session_refused")
            self._session_refused = True
            self._session = None
            return None
        self._session = {
            "id": resp.session_id,
            "fp": resp.epoch_fingerprint,
            "tick": 0,
            "p_cols": p_cols,
            "r_cols": r_cols,
            "params": params,
        }
        self._backend_ms.append(resp.result.solve_ms)
        return _res_v2(resp.result)

    # ---------------- matcher integration ----------------

    def _native_kernel(self) -> str:
        if self.native_engine in ("native-mt", "sinkhorn-mt"):
            return self.native_engine + (
                f":{self.native_threads}" if self.native_threads else ""
            )
        if self.native_engine.partition(":")[0] == "jax":
            # first-class engine, same suffix convention (jax[:D], D =
            # sharded-gen devices; a bare "jax" picks the suffix up from
            # native_threads like the native engines do). NEVER demoted
            # to "native" — a silent cross-engine swap would invalidate
            # every replay A/B keyed on the session kernel string.
            if ":" in self.native_engine or not self.native_threads:
                return self.native_engine
            return f"jax:{self.native_threads}"
        return "native"

    def _bounded_t4p(self, ep, er) -> np.ndarray:
        if self.native_fallback:
            # engine=native-mt rides the wire as a kernel-string suffix so
            # the backend's warm arena (and its thread pool) do the work;
            # on wire=v2 it rides the session protocol instead and only
            # churned rows hit the wire
            res = self._call(
                ep, er, self._native_kernel(), eps=0.02, max_iters=0
            )
            return np.asarray(res.t4p, np.int32)
        res = self._call(ep, er, "auction", eps=0.05, max_iters=300)
        return np.asarray(res.t4p, np.int32)

    def _bounded_t4p_sparse(
        self, ep, er, price0: np.ndarray, p4s0: np.ndarray, warm: bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Scale path over the wire: the backend's "topk" kernel, with the
        incremental-solve state (prices + previous matching) riding the
        request/response so the backend stays stateless across replicas."""
        n_p = int(np.asarray(ep.valid).sum())
        n_s = int(np.asarray(er.valid).sum())
        warm_price = seed = None
        if warm:
            warm_price = np.asarray(price0[:n_p], np.float32)
            seed = np.asarray(p4s0[:n_s], np.int32)
        res = self._call(
            ep, er, "topk", eps=0.02, max_iters=0,
            warm_price=warm_price, seed_p4t=seed, top_k=self.top_k,
        )
        price = (
            res.price if res.price is not None
            else np.zeros(n_p, np.float32)
        )
        return (
            np.asarray(res.t4p, np.int32),
            np.asarray(price, np.float32),
        )

    def _unbounded_best(self, ep, er) -> np.ndarray:
        res = self._call(ep, er, "best", eps=0.0, max_iters=0)
        return np.asarray(res.t4p, np.int32)
