"""Ledger HTTP API: the devnet's chain-RPC endpoint.

The reference dev environment runs a local Ethereum devnet (reth) that every
service and the dev-utils CLIs talk to over JSON-RPC (docker-compose.yml,
Makefile). This service is that seam for the in-process ledger: a small
HTTP API exposing the contract-wrapper surface so CLIs, tests, and
out-of-process services share one economic substrate.

Write ops are admin-key gated (the devnet holds the faucet); reads are open.
POST /ledger/{op} with a JSON params object; responses are
{"success": bool, "data"|"error": ...}.
"""

from __future__ import annotations

import json
from typing import Any

from aiohttp import web

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.security.middleware import api_key_middleware

WRITE_OPS = {
    "mint",
    "transfer",
    "approve",
    "create_domain",
    "register_provider",
    "increase_stake",
    "reclaim_stake",
    "whitelist_provider",
    "add_compute_node",
    "remove_compute_node",
    "validate_node",
    "create_pool",
    "start_pool",
    "join_compute_pool",
    "eject_node",
    "blacklist_node",
    "submit_work",
    "invalidate_work",
    "soft_invalidate_work",
    "leave_compute_pool",
    "grant_validator_role",
    "revoke_validator_role",
}

READ_OPS = {
    "balance_of",
    "get_domain",
    "provider_exists",
    "get_provider",
    "get_stake",
    "is_provider_whitelisted",
    "node_exists",
    "get_node",
    "is_node_validated",
    "get_provider_total_compute",
    "get_pool_info",
    "is_node_in_pool",
    "get_work_keys",
    "get_work_info",
    "get_work_since",
    "get_rewards",
    "calculate_stake",
    "get_validator_role",
}


def _jsonable(value: Any) -> Any:
    import enum

    if isinstance(value, enum.Enum):
        # must precede the __dict__ branch: enum members have a __dict__ of
        # private fields that would serialize as {}
        return value.value
    if hasattr(value, "__dict__"):
        return {
            k: _jsonable(v)
            for k, v in vars(value).items()
            if not k.startswith("_")
        }
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, set):
        return sorted(value)
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if hasattr(value, "value") and not isinstance(value, (int, float, str, bool)):
        return value.value  # enums
    return value


class LedgerApiService:
    def __init__(self, ledger: Ledger, admin_api_key: str = "admin"):
        self.ledger = ledger
        self.admin_api_key = admin_api_key

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[api_key_middleware(self.admin_api_key, ["/ledger/write"])]
        )
        app.router.add_post("/ledger/write/{op}", self.write_op)
        app.router.add_post("/ledger/read/{op}", self.read_op)
        app.router.add_get("/health", self.health)
        return app

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def _call(self, op: str, allowed: set[str], request: web.Request) -> web.Response:
        if op not in allowed:
            return web.json_response(
                {"success": False, "error": f"unknown op {op}"}, status=404
            )
        try:
            params = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            return web.json_response(
                {"success": False, "error": "invalid json"}, status=400
            )
        try:
            result = getattr(self.ledger, op)(**params)
        except LedgerError as e:
            return web.json_response({"success": False, "error": str(e)}, status=400)
        except TypeError as e:
            return web.json_response(
                {"success": False, "error": f"bad params: {e}"}, status=400
            )
        return web.json_response({"success": True, "data": _jsonable(result)})

    async def write_op(self, request: web.Request) -> web.Response:
        return await self._call(request.match_info["op"], WRITE_OPS, request)

    async def read_op(self, request: web.Request) -> web.Response:
        return await self._call(request.match_info["op"], READ_OPS, request)
