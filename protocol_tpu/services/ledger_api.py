"""Ledger HTTP API: the devnet's chain-RPC endpoint.

The reference dev environment runs a local Ethereum devnet (reth) that every
service and the dev-utils CLIs talk to over JSON-RPC (docker-compose.yml,
Makefile). This service is that seam for the in-process ledger: a small
HTTP API exposing the contract-wrapper surface so CLIs, tests, and
out-of-process services share one economic substrate.

Write ops are admin-key gated (the devnet holds the faucet); reads are open.
POST /ledger/{op} with a JSON params object; responses are
{"success": bool, "data"|"error": ...}.
"""

from __future__ import annotations

import json
import time
from typing import Any

from aiohttp import web

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.security.middleware import api_key_middleware

WRITE_OPS = {
    "mint",
    "transfer",
    "approve",
    "create_domain",
    "register_provider",
    "increase_stake",
    "reclaim_stake",
    "whitelist_provider",
    "add_compute_node",
    "remove_compute_node",
    "validate_node",
    "create_pool",
    "start_pool",
    "join_compute_pool",
    "eject_node",
    "blacklist_node",
    "submit_work",
    "invalidate_work",
    "soft_invalidate_work",
    "leave_compute_pool",
    "grant_validator_role",
    "revoke_validator_role",
}

READ_OPS = {
    "balance_of",
    "get_domain",
    "provider_exists",
    "get_provider",
    "get_stake",
    "is_provider_whitelisted",
    "node_exists",
    "get_node",
    "is_node_validated",
    "get_provider_total_compute",
    "get_pool_info",
    "is_node_in_pool",
    "get_work_keys",
    "get_work_info",
    "get_work_since",
    "get_rewards",
    "calculate_stake",
    "get_validator_role",
}


def _jsonable(value: Any) -> Any:
    import enum

    if isinstance(value, enum.Enum):
        # must precede the __dict__ branch: enum members have a __dict__ of
        # private fields that would serialize as {}
        return value.value
    if hasattr(value, "__dict__"):
        return {
            k: _jsonable(v)
            for k, v in vars(value).items()
            if not k.startswith("_")
        }
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, set):
        return sorted(value)
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if hasattr(value, "value") and not isinstance(value, (int, float, str, bool)):
        return value.value  # enums
    return value


class LedgerApiService:
    # tx_id -> (expiry, response payload). The HTTP analog of the
    # reference's receipt check in retry_call
    # (crates/shared/src/web3/contracts/helpers/utils.rs:22-70): a client
    # retrying a write whose RESPONSE was lost must not double-apply the
    # transaction, so writes carrying a tx_id are deduplicated and the
    # recorded outcome is replayed.
    _TX_TTL = 600.0

    def __init__(self, ledger: Ledger, admin_api_key: str = "admin"):
        self.ledger = ledger
        self.admin_api_key = admin_api_key
        self._tx_seen: dict[str, tuple[float, dict]] = {}

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[api_key_middleware(self.admin_api_key, ["/ledger/write"])]
        )
        app.router.add_post("/ledger/write/{op}", self.write_op)
        app.router.add_post("/ledger/read/{op}", self.read_op)
        app.router.add_get("/health", self.health)
        return app

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def _call(
        self, op: str, allowed: set[str], request: web.Request,
        dedup: bool = False,
    ) -> web.Response:
        if op not in allowed:
            return web.json_response(
                {"success": False, "error": f"unknown op {op}"}, status=404
            )
        try:
            params = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            return web.json_response(
                {"success": False, "error": "invalid json"}, status=400
            )
        if not isinstance(params, dict):
            return web.json_response(
                {"success": False, "error": "bad params: body must be an object"},
                status=400,
            )
        # tx_id dedup is a WRITE-path facility (dedup=True): the write
        # routes are admin-key gated, so only authenticated writers can
        # populate the cache — reads accepting tx_id would hand
        # unauthenticated callers an unbounded-memory lever
        tx_id = params.pop("tx_id", None) if dedup else None
        if tx_id is not None:
            now = time.monotonic()
            hit = self._tx_seen.get(str(tx_id))
            if hit is not None and hit[0] > now:
                payload, status = hit[1]
                return web.json_response(payload, status=status)
        try:
            result = getattr(self.ledger, op)(**params)
            payload, status = {"success": True, "data": _jsonable(result)}, 200
        except LedgerError as e:
            payload, status = {"success": False, "error": str(e)}, 400
        except TypeError as e:
            payload, status = {"success": False, "error": f"bad params: {e}"}, 400
        if tx_id is not None:
            # record the outcome (success OR application error: a retry of
            # a rejected tx must replay the rejection, not re-run it) and
            # sweep expired entries
            now = time.monotonic()
            self._tx_seen = {
                k: v for k, v in self._tx_seen.items() if v[0] > now
            }
            self._tx_seen[str(tx_id)] = (now + self._TX_TTL, (payload, status))
        return web.json_response(payload, status=status)

    async def write_op(self, request: web.Request) -> web.Response:
        return await self._call(
            request.match_info["op"], WRITE_OPS, request, dedup=True
        )

    async def read_op(self, request: web.Request) -> web.Response:
        return await self._call(request.match_info["op"], READ_OPS, request)
