"""Container task runtime driving the docker CLI.

Counterpart of the reference worker's Docker execution model:

  - crates/worker/src/docker/docker_manager.rs:1-850 — bollard client:
    pull, create (GPU device requests, volumes, host networking unless
    disabled, shm sizing), start/stop/remove/inspect/logs
  - crates/worker/src/docker/service.rs:56-295 — 5 s reconcile loop:
    container identity ``prime-task-{id}-{confighash}``, stale-container
    removal, ${SOCKET_PATH} expansion, NODE_ADDRESS / PRIME_TASK_ID
    injection, socket-dir + task volume mounts, shm = RAM/2, restart
    backoff + consecutive-failure count, container status -> TaskState

Instead of a daemon-API client library this drives the ``docker`` CLI
through asyncio subprocesses: same lifecycle semantics, zero extra
dependencies, and tests interpose a fake ``docker`` binary on PATH (the
role bollard fakes play in the reference's tests). All state queries are
cached at reconcile time so the synchronous ``state()`` contract of
``TaskRuntime`` holds between ticks, like the reference's DockerState.
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import time
from typing import Optional

from protocol_tpu.models.heartbeat import TaskDetails
from protocol_tpu.models.task import Task, TaskState

from .worker import RESTART_BACKOFF_SECONDS, TaskRuntime

TASK_PREFIX = "prime-task"

# container status -> TaskState (service.rs:267-281)
_STATUS_MAP = {
    "running": TaskState.RUNNING,
    "created": TaskState.PENDING,
    "dead": TaskState.FAILED,
    "paused": TaskState.PAUSED,
    "restarting": TaskState.RESTARTING,
}


class DockerCliError(RuntimeError):
    pass


class DockerCli:
    """Minimal async wrapper over the docker CLI (the docker_manager.rs
    surface this framework needs)."""

    def __init__(self, docker_bin: str = "docker"):
        self.docker_bin = docker_bin

    async def _run(self, *args: str, check: bool = True) -> str:
        proc = await asyncio.create_subprocess_exec(
            self.docker_bin,
            *args,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate()
        if check and proc.returncode != 0:
            raise DockerCliError(
                f"docker {' '.join(args[:2])} failed rc={proc.returncode}: "
                f"{err.decode(errors='replace').strip()[:500]}"
            )
        return out.decode(errors="replace")

    async def list_task_containers(self) -> list[str]:
        """Names of all prime-task-* containers, running or not."""
        out = await self._run(
            "ps", "-a", "--filter", f"name={TASK_PREFIX}", "--format", "{{.Names}}"
        )
        return [line.strip() for line in out.splitlines() if line.strip()]

    async def remove(self, name: str) -> None:
        await self._run("rm", "-f", name, check=False)

    async def restart(self, name: str) -> None:
        await self._run("restart", name, check=False)

    async def logs(self, name: str, tail: int = 100) -> str:
        return await self._run("logs", "--tail", str(tail), name, check=False)

    async def inspect_state(self, name: str) -> Optional[dict]:
        """{'status': str, 'exit_code': int, 'id': str, 'image': str} or
        None when the container does not exist."""
        out = await self._run(
            "inspect",
            "--format",
            '{"status":"{{.State.Status}}","exit_code":{{.State.ExitCode}},'
            '"id":"{{.Id}}","image":"{{.Config.Image}}"}',
            name,
            check=False,
        )
        out = out.strip()
        if not out.startswith("{"):
            return None
        try:
            return json.loads(out)
        except json.JSONDecodeError:
            return None

    async def run_detached(
        self,
        name: str,
        image: str,
        cmd: list[str],
        env: dict[str, str],
        volumes: list[tuple[str, str, bool]],  # (host, container, read_only)
        shm_size_bytes: Optional[int] = None,
        gpu_device_ids: Optional[list[str]] = None,
        entrypoint: Optional[list[str]] = None,
        host_network: bool = True,
    ) -> str:
        """docker run -d with the reference's HostConfig surface
        (docker_manager.rs:397-440): host networking by default, GPU
        device requests, shm sizing, bind mounts."""
        args: list[str] = ["run", "-d", "--name", name]
        if host_network:
            args += ["--network", "host"]
        if shm_size_bytes:
            args += ["--shm-size", str(shm_size_bytes)]
        if gpu_device_ids is not None:
            spec = (
                "all"
                if not gpu_device_ids
                else "device=" + ",".join(gpu_device_ids)
            )
            args += ["--gpus", spec]
        for key, value in env.items():
            args += ["-e", f"{key}={value}"]
        for host, container, read_only in volumes:
            args += ["-v", f"{host}:{container}" + (":ro" if read_only else "")]
        full_cmd = list(cmd)
        if entrypoint:
            # CLI --entrypoint takes one binary; extra entrypoint args are
            # prepended to the command (same process argv as the API path)
            args += ["--entrypoint", entrypoint[0]]
            full_cmd = list(entrypoint[1:]) + full_cmd
        args.append(image)
        args += full_cmd
        out = await self._run(*args)
        return out.strip()


class DockerRuntime(TaskRuntime):
    """TaskRuntime backed by containers (docker/service.rs semantics)."""

    def __init__(
        self,
        socket_path: Optional[str] = None,
        docker_bin: str = "docker",
        system_memory_mb: Optional[int] = None,
        gpu_device_ids: Optional[list[str]] = None,  # None = no GPU request
        host_network: bool = True,
        slot: Optional[str] = None,  # colocation: per-runtime sub-namespace
    ):
        self.cli = DockerCli(docker_bin)
        self.socket_path = socket_path
        self.slot = slot
        self.system_memory_mb = system_memory_mb
        self.gpu_device_ids = gpu_device_ids
        self.host_network = host_network

        self.current: Optional[Task] = None
        self.failures = 0
        self.last_started = 0.0
        self.logs: list[str] = []
        self._diag: list[str] = []  # start/daemon errors, kept across ticks
        self._scope: Optional[str] = None  # per-node container namespace
        self._cached_state: tuple[Optional[str], TaskState, Optional[TaskDetails]] = (
            None,
            TaskState.UNKNOWN,
            None,
        )
        self._current_name: Optional[str] = None
        self._last_task_state: Optional[TaskState] = None

    # container identity: node scope (+ colocation slot) + task id +
    # config hash, so any env/cmd/image change is a different container
    # (service.rs:69-74). The node scope keeps workers sharing one docker
    # daemon (devnet) from reconciling away each other's containers — the
    # reference assumes one worker per dockerd and needs no scope. The
    # SLOT does the same between a node's own colocated runtimes: the
    # stale-container sweep in reconcile_once removes everything under
    # this runtime's prefix, so without a per-runtime slot the primary
    # and each extra would destroy each other's containers every beat
    # (and apply(None) on a departing extra would sweep the whole node).
    # The slot segment is "s" + 8 hex, unambiguous against task-id
    # segments (uuid hex never starts with "s"), so the slotless primary
    # can recognize — and skip — foreign slotted containers.
    def _name_prefix(self) -> str:
        parts = [TASK_PREFIX]
        if self._scope:
            parts.append(self._scope)
        if self.slot:
            parts.append(f"s{self.slot}")
        return "-".join(parts)

    @staticmethod
    def _is_slotted(rest: str) -> bool:
        """Does the post-prefix remainder start with a slot segment?"""
        return bool(re.match(r"^s[0-9a-f]{8}-", rest))

    def container_name(self, task: Task) -> str:
        return f"{self._name_prefix()}-{task.id}-{task.generate_config_hash()[:16]}"

    async def apply(self, task: Optional[Task], node_address: str) -> None:
        self.current = task
        self._scope = node_address[-8:].lower() if node_address else None
        await self.reconcile_once(node_address)

    async def reconcile_once(self, node_address: str) -> None:
        """One reconcile tick (service.rs:56-295): remove stale task
        containers, start the current task's container if absent (with
        restart backoff), refresh the cached state from docker."""
        task = self.current
        expected = self.container_name(task) if task else None
        if expected != self._current_name:
            # task identity changed: per-task counters restart
            self._current_name = expected
            self._last_task_state = None
            self.failures = 0
        try:
            names = await self.cli.list_task_containers()
        except (DockerCliError, OSError) as e:
            self._diag.append(f"docker unavailable: {e}")
            # never report the previous container's state while blind
            self._cached_state = (
                task.id if task else None,
                TaskState.UNKNOWN,
                TaskDetails(error_message=str(e)[:500]) if task else None,
            )
            self._compose_logs(None)
            return

        prefix = self._name_prefix() + "-"
        for name in names:
            if not name.startswith(prefix) or name == expected:
                continue
            if self.slot is None and self._is_slotted(name[len(prefix):]):
                # a colocated sibling's container, not this slot's stale
                continue
            await self.cli.remove(name)

        if task is None or expected is None:
            self._cached_state = (None, TaskState.UNKNOWN, None)
            return

        state = await self.cli.inspect_state(expected)
        if state is not None and state.get("status") == "exited" and state.get(
            "exit_code"
        ):
            # crashed container: count the failure, then remove + restart
            # once past the backoff (SubprocessRuntime semantics; the
            # reference leaves crashed containers dead until an operator
            # /restart — restarting with backoff strictly improves on that)
            self._refresh_cache(task, state)
            if time.monotonic() - self.last_started < RESTART_BACKOFF_SECONDS:
                return
            await self.cli.remove(expected)
            state = None

        if state is None:
            # container missing -> start, honoring the restart backoff
            # (service.rs:160-175)
            if time.monotonic() - self.last_started < RESTART_BACKOFF_SECONDS:
                self._cached_state = (task.id, TaskState.PENDING, None)
                return
            await self._start(task, expected, node_address)
            state = await self.cli.inspect_state(expected)

        self._refresh_cache(task, state)

    def _compose_logs(self, raw: Optional[str]) -> None:
        """Container logs plus retained runtime diagnostics, so /logs still
        explains past start failures after the container is recreated."""
        self._diag = self._diag[-100:]
        lines = raw.splitlines()[-1000:] if raw else []
        self.logs = self._diag + lines

    async def get_logs(self) -> list[str]:
        """On-demand container logs (+diagnostics) for the /control/logs
        surface; logs are NOT fetched every reconcile tick — that would
        fork a docker subprocess per heartbeat for output nobody reads."""
        if self.current is not None:
            try:
                self._compose_logs(
                    await self.cli.logs(self.container_name(self.current))
                )
            except (DockerCliError, OSError):
                self._compose_logs(None)
        return self.logs

    async def _start(self, task: Task, name: str, node_address: str) -> None:
        sock = self.socket_path or ""
        expand = lambda s: s.replace("${SOCKET_PATH}", sock)  # noqa: E731

        cmd = [expand(c) for c in (task.cmd or [])]
        if not cmd and not task.entrypoint:
            # idle placeholder only when the task specifies NO process at
            # all (service.rs:184-188); with an entrypoint, leave argv empty
            cmd = ["sleep", "infinity"]
        env = {k: expand(v) for k, v in (task.env_vars or {}).items()}
        env["NODE_ADDRESS"] = node_address
        env["PRIME_TASK_ID"] = str(task.id)
        volumes: list[tuple[str, str, bool]] = []
        if sock:
            env["PRIME_MONITOR__SOCKET__PATH"] = sock
            sock_dir = os.path.dirname(sock)
            volumes.append((sock_dir, sock_dir, False))
        for vm in task.volume_mounts or []:
            volumes.append((vm.host_path, vm.container_path, False))
        # shm = RAM/2 (service.rs:222-228); 64 MB default like the reference
        shm = (
            self.system_memory_mb * 1024 * 1024 // 2
            if self.system_memory_mb
            else 64 * 1024 * 1024
        )
        self.last_started = time.monotonic()
        try:
            await self.cli.run_detached(
                name,
                task.image,
                cmd,
                env,
                volumes,
                shm_size_bytes=shm,
                gpu_device_ids=self.gpu_device_ids,
                entrypoint=task.entrypoint,
                host_network=self.host_network,
            )
        except (DockerCliError, OSError) as e:
            self._diag.append(f"container start failed: {e}")
            self._compose_logs(None)
            self.failures += 1
            self._cached_state = (
                task.id,
                TaskState.FAILED,
                TaskDetails(error_message=str(e)[:500]),
            )

    def _refresh_cache(self, task: Task, state: Optional[dict]) -> None:
        if state is None:
            return  # start already cached FAILED, or PENDING backoff
        status = state.get("status", "")
        exit_code = state.get("exit_code")
        if status == "exited":
            ts = (
                TaskState.COMPLETED
                if exit_code == 0
                else (TaskState.FAILED if exit_code is not None else TaskState.UNKNOWN)
            )
        else:
            ts = _STATUS_MAP.get(status, TaskState.UNKNOWN)
        # consecutive-failure counting on state CHANGES (service.rs:283-295)
        if ts != self._last_task_state:
            if ts == TaskState.FAILED:
                self.failures += 1
            elif ts == TaskState.RUNNING:
                self.failures = 0
            self._last_task_state = ts
        self._cached_state = (
            task.id,
            ts,
            TaskDetails(
                container_id=state.get("id"),
                container_status=status,
                exit_code=exit_code if status == "exited" else None,
            ),
        )

    async def restart_task(self) -> None:
        """Explicit restart of the current container (service.rs:332-343)."""
        if self.current is not None:
            await self.cli.restart(self.container_name(self.current))

    def state(self) -> tuple[Optional[str], TaskState, Optional[TaskDetails]]:
        if self.current is None:
            return None, TaskState.UNKNOWN, None
        return self._cached_state
