"""Server-side session registry for wire protocol v2 (session epochs).

A session pins the expensive per-marketplace state — the full columnar
snapshot plus a persistent :class:`NativeSolveArena` — behind a
``(session_id, epoch_fingerprint)`` key, so steady-state ticks ship only
churned rows over the wire (``AssignDelta``) and the warm candidate
structure + auction duals never leave the server. This is what turns
PR 1's warm-solve win from a local-process property into an end-to-end
RPC property: the wire cost per tick becomes O(churn), matching the
solve cost.

Any replica must be able to serve any solve: an ``AssignDelta`` against
a session this process does not hold (or holds under a different epoch
fingerprint / tick cursor) is REFUSED, never guessed at — the client
falls back down the ladder (fresh snapshot stream -> stateless v1).
Sessions are LRU-evicted beyond ``max_sessions`` and expire after
``ttl_s`` idle seconds; eviction is always safe because the client can
re-open from its own authoritative state.

Delta application is copy-on-write per column: the arena's dirty
detection holds the PREVIOUS tick's columns by reference (copying every
column per solve would dominate at 1M rows), so a churned column is
replaced, never mutated in place — untouched columns stay shared.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from protocol_tpu.obs.spans import TRACER as _tracer
from protocol_tpu.proto.wire import P_WIRE_DTYPES, R_WIRE_DTYPES

# session-servable kernel strings -> the arena engine behind them
_SESSION_ENGINES = {"native-mt": "auction", "sinkhorn-mt": "sinkhorn"}


def parse_session_kernel(kernel: str) -> Optional[tuple[str, int]]:
    """``native-mt[:N]`` / ``sinkhorn-mt[:N]`` -> (arena engine, thread
    count; 0 = all hardware threads). Any other kernel -> None (not
    session-servable: the session protocol's warm state lives in the
    native arena)."""
    base, _, suffix = kernel.partition(":")
    engine = _SESSION_ENGINES.get(base)
    if engine is None:
        return None
    try:
        return engine, (int(suffix) if suffix else 0)
    except ValueError:
        return None


def parse_native_threads(kernel: str) -> Optional[int]:
    """Thread count of a session-servable kernel string, None otherwise
    (back-compat shim over :func:`parse_session_kernel`)."""
    parsed = parse_session_kernel(kernel)
    return None if parsed is None else parsed[1]


class EngineThreadBudget:
    """Bounded native-engine thread budget shared across concurrent
    solves. The gRPC servicer runs a thread pool, and every session holds
    its own arena behind its own lock — without a shared budget, two
    concurrent solves each asking for "all hardware threads" oversubscribe
    the host 2x (and N sessions, Nx).

    Each solve acquires a grant of min(requested, available) threads and
    releases it when done. ``acquire`` NEVER BLOCKS: a fully-drained pool
    degrades the grant to a single thread instead of parking the caller —
    blocking would re-create exactly the solve serialization the
    per-session locks removed (the default kernel string requests "all
    hardware threads", so the first solve would drain the pool and every
    concurrent session would queue behind it). The worst case is a
    bounded oversubscription of one thread per concurrent solve (capped
    by the server's worker pool), not Nx total. The native engines are
    bit-identical for every thread count, so a degraded grant can change
    wall-clock but never a result."""

    def __init__(self, total: Optional[int] = None):
        self.total = int(total) if total else (os.cpu_count() or 1)
        self._avail = self.total
        self._lock = threading.Lock()
        # obs plane counters (read by ObsRegistry's budget gauges):
        # cumulative grants, grants smaller than requested (the
        # saturation signal the fleet roadmap gates on), and the lowest
        # availability ever observed
        self.grants = 0
        self.degraded_grants = 0
        self.min_avail = self.total

    def acquire(self, want: int) -> int:
        """Returns the grant size (>= 1, never blocks)."""
        want = self.total if want <= 0 else min(int(want), self.total)
        with self._lock:
            grant = max(1, min(want, self._avail))
            self._avail -= grant
            self.grants += 1
            if grant < want:
                self.degraded_grants += 1
            if self._avail < self.min_avail:
                self.min_avail = self._avail
        _tracer.point("budget.grant", want=want, grant=grant)
        return grant

    def release(self, grant: int) -> None:
        with self._lock:
            self._avail += int(grant)

    @property
    def available(self) -> int:
        """Uncommitted thread capacity (negative under the bounded
        oversubscription a contended 1-thread floor grant allows)."""
        with self._lock:
            return self._avail


def _pad_cols(cols: dict[str, np.ndarray], n_real: int) -> dict[str, np.ndarray]:
    """Pad columns to the next pow2 bucket with valid=False rows — the
    same bucketing contract as scheduler_grpc._pad_pow2 (zero fill +
    valid mask), so session solves and unary solves see bit-identical
    padded inputs."""
    if n_real <= 0:
        return dict(cols)
    target = 1 << (n_real - 1).bit_length()
    if target == n_real:
        return dict(cols)
    out = {}
    for name, a in cols.items():
        pad = [(0, target - n_real)] + [(0, 0)] * (a.ndim - 1)
        out[name] = np.pad(a, pad)
    out["valid"] = np.concatenate(
        [np.asarray(cols["valid"], bool)[:n_real],
         np.zeros(target - n_real, bool)]
    )
    return out


def _as_ns(cols: dict[str, np.ndarray]) -> object:
    ns = type("_Cols", (), {})()
    for name, arr in cols.items():
        setattr(ns, name, arr)
    return ns


@dataclass
class SolveSession:
    session_id: str
    fingerprint: str
    weights: object  # CostWeights
    kernel: str
    threads: int
    top_k: int
    p_cols: dict  # padded, wire dtypes
    r_cols: dict
    n_providers: int  # real (unpadded) row counts
    n_tasks: int
    arena: object  # NativeSolveArena
    tick: int = 0
    last_used: float = field(default_factory=time.monotonic)
    lock: threading.Lock = field(default_factory=threading.Lock)
    delta_rows_total: int = 0
    # set (under the store lock) when the store lets go of this session —
    # LRU eviction, TTL expiry, drop, or same-id replacement. An in-flight
    # AssignDelta that already looked the session up must REFUSE after
    # seeing this instead of solving against (and advancing the tick of)
    # an arena the store no longer owns: the client's next delta would be
    # refused anyway ("unknown session"), but its shadow columns would
    # have silently diverged from a solve nobody can replay.
    evicted: bool = False
    # shared EngineThreadBudget (None = unbudgeted, use arena.threads)
    budget: object = None
    # flight recorder (trace.recorder.TraceRecorder) when this session
    # claimed the PROTOCOL_TPU_TRACE stream: every APPLIED delta lands
    # its exact wire rows from apply_delta (refused deltas never record)
    trace: object = None

    def solve(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the warm arena over the current columns; returns
        (provider_for_task[T], task_for_provider[P], price[P]) over the
        REAL row counts. With a ``budget`` attached, the solve borrows a
        bounded thread grant so concurrent sessions share the host's
        cores instead of oversubscribing them (results are thread-count
        invariant, so the grant size never changes the matching)."""
        grant = None
        if self.budget is not None:
            grant = self.budget.acquire(self.threads)
            self.arena.threads = grant
        try:
            p4t_full = self.arena.solve(
                _as_ns(self.p_cols), _as_ns(self.r_cols), self.weights
            )
        finally:
            if grant is not None:
                self.budget.release(grant)
        p4t = np.asarray(p4t_full)[: self.n_tasks]
        t4p = np.full(self.n_providers, -1, np.int32)
        seated = np.flatnonzero((p4t >= 0) & (p4t < self.n_providers))
        t4p[p4t[seated]] = seated.astype(np.int32)
        price = np.asarray(self.arena.price)[: self.n_providers]
        return p4t, t4p, price

    def apply_delta(
        self,
        provider_rows: np.ndarray,
        p_delta: dict[str, np.ndarray],
        task_rows: np.ndarray,
        r_delta: dict[str, np.ndarray],
    ) -> int:
        """Write churned rows into the session columns, copy-on-write per
        column. Returns the number of rows actually applied. Row indices
        are validated against the REAL row space — padding rows are the
        server's own invention and never addressable from the wire."""
        groups = (
            (provider_rows, p_delta, self.p_cols, self.n_providers,
             P_WIRE_DTYPES),
            (task_rows, r_delta, self.r_cols, self.n_tasks, R_WIRE_DTYPES),
        )
        # validate EVERYTHING before the first write: a mid-application
        # raise would leave the session half-mutated with an unadvanced
        # tick — state matching no client's shadow copy anywhere
        for rows, delta, _cols, n_real, spec in groups:
            if rows.size == 0:
                continue
            if rows.min() < 0 or rows.max() >= n_real:
                raise ValueError(
                    f"delta row index out of range [0, {n_real})"
                )
            for name in spec:
                if np.asarray(delta[name]).shape[0] != rows.size:
                    # without this, numpy BROADCASTS a 1-row payload into
                    # every indexed row and the server acks a delta whose
                    # columns silently diverged from the client's shadow
                    # copy — the exact divergence the tick/fingerprint
                    # machinery exists to refuse
                    raise ValueError(
                        f"delta column {name!r} has "
                        f"{np.asarray(delta[name]).shape[0]} rows for "
                        f"{rows.size} row indices"
                    )
        applied = 0
        for rows, delta, cols, _n_real, spec in groups:
            if rows.size == 0:
                continue
            for name in spec:
                new_vals = delta[name]
                if np.array_equal(cols[name][rows], new_vals):
                    continue  # column untouched by this delta
                col = cols[name].copy()
                col[rows] = new_vals
                cols[name] = col
            applied += int(rows.size)
        self.delta_rows_total += applied
        if self.trace is not None:
            from protocol_tpu.trace.recorder import safe as _trace_safe

            # the delta for the tick the caller is about to advance to
            # (callers hold self.lock here, so tick+1 cannot race);
            # empty deltas record too — a no-churn tick still solves,
            # and replay regenerates the tick sequence from these frames
            _trace_safe(
                self.trace.record_session_delta, self.session_id,
                self.tick + 1, provider_rows, p_delta, task_rows, r_delta,
            )
        return applied


class SessionStore:
    """LRU + TTL registry of :class:`SolveSession`."""

    def __init__(self, max_sessions: int = 8, ttl_s: float = 900.0):
        self.max_sessions = max_sessions
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._sessions: OrderedDict[str, SolveSession] = OrderedDict()
        self.evictions = 0
        self.expirations = 0

    def _expire_locked(self) -> None:
        now = time.monotonic()
        dead = [
            sid for sid, s in self._sessions.items()
            if now - s.last_used > self.ttl_s
        ]
        for sid in dead:
            self._sessions[sid].evicted = True
            del self._sessions[sid]
            self.expirations += 1
            _tracer.point("session.evict", session=sid, reason="ttl")

    def put(self, session: SolveSession) -> None:
        with self._lock:
            self._expire_locked()
            replaced = self._sessions.pop(session.session_id, None)
            if replaced is not None:
                replaced.evicted = True
            self._sessions[session.session_id] = session
            while len(self._sessions) > self.max_sessions:
                sid, lru = self._sessions.popitem(last=False)
                lru.evicted = True
                self.evictions += 1
                _tracer.point("session.evict", session=sid, reason="lru")

    def get(
        self, session_id: str, fingerprint: str
    ) -> tuple[Optional[SolveSession], str]:
        """Look up a session for a delta tick. Returns (session, "") on
        hit or (None, reason) — reason is wire-safe text the client logs."""
        with _tracer.span("session.lookup", session=session_id):
            with self._lock:
                self._expire_locked()
                s = self._sessions.get(session_id)
                if s is None:
                    return None, "unknown session"
                if s.fingerprint != fingerprint:
                    return None, "epoch fingerprint mismatch"
                self._sessions.move_to_end(session_id)
                s.last_used = time.monotonic()
                return s, ""

    def drop(self, session_id: str) -> None:
        with self._lock:
            dropped = self._sessions.pop(session_id, None)
            if dropped is not None:
                dropped.evicted = True
                _tracer.point(
                    "session.evict", session=session_id, reason="drop"
                )

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)
