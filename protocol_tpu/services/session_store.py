"""Server-side session registry for wire protocol v2 (session epochs).

A session pins the expensive per-marketplace state — the full columnar
snapshot plus a persistent :class:`NativeSolveArena` — behind a
``(session_id, epoch_fingerprint)`` key, so steady-state ticks ship only
churned rows over the wire (``AssignDelta``) and the warm candidate
structure + auction duals never leave the server. This is what turns
PR 1's warm-solve win from a local-process property into an end-to-end
RPC property: the wire cost per tick becomes O(churn), matching the
solve cost.

Any replica must be able to serve any solve: an ``AssignDelta`` against
a session this process does not hold (or holds under a different epoch
fingerprint / tick cursor) is REFUSED, never guessed at — the client
falls back down the ladder (fresh snapshot stream -> stateless v1).
Sessions are LRU-evicted beyond ``max_sessions`` and expire after
``ttl_s`` idle seconds; eviction is always safe because the client can
re-open from its own authoritative state.

Delta application is copy-on-write per column: the arena's dirty
detection holds the PREVIOUS tick's columns by reference (copying every
column per solve would dominate at 1M rows), so a churned column is
replaced, never mutated in place — untouched columns stay shared.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from protocol_tpu.obs.spans import TRACER as _tracer
from protocol_tpu.proto.wire import P_WIRE_DTYPES, R_WIRE_DTYPES
from protocol_tpu.utils.lockwitness import make_lock

# session-servable kernel strings -> the arena engine behind them
_SESSION_ENGINES = {
    "native-mt": "auction",
    "sinkhorn-mt": "sinkhorn",
    "jax": "jax",
}


def _session_lock():
    return make_lock("session")


def _inflight_lock():
    return make_lock("inflight")


def parse_session_kernel(kernel: str) -> Optional[tuple[str, int]]:
    """``native-mt[:N]`` / ``sinkhorn-mt[:N]`` / ``jax[:D]`` ->
    (arena engine, thread count; 0 = all hardware threads — for the jax
    engine the suffix is the DEVICE count instead, 0 = all visible).
    Any other kernel -> None (not session-servable: the session
    protocol's warm state lives in a solve arena)."""
    base, _, suffix = kernel.partition(":")
    engine = _SESSION_ENGINES.get(base)
    if engine is None:
        return None
    try:
        return engine, (int(suffix) if suffix else 0)
    except ValueError:
        return None


def make_solve_arena(engine: str, k: int, threads: int, **kw):
    """One home for arena construction from a parsed kernel string —
    the engine seam every server surface routes through (sessions, the
    unary persistent arena, checkpoint restore). ``engine="jax"``
    returns the accelerator-path :class:`~protocol_tpu.parallel.
    jax_arena.JaxSolveArena` (``threads`` becomes its sharded-gen
    device count; 0 = all visible devices, the mesh analog of "all
    hardware threads"); anything else is a
    :class:`~protocol_tpu.native.arena.NativeSolveArena` engine."""
    if engine == "jax":
        from protocol_tpu.parallel.jax_arena import JaxSolveArena

        return JaxSolveArena(k=k, devices=threads, **kw)
    from protocol_tpu.native.arena import NativeSolveArena

    return NativeSolveArena(k=k, threads=threads, engine=engine, **kw)


def parse_native_threads(kernel: str) -> Optional[int]:
    """Thread count of a session-servable kernel string, None otherwise
    (back-compat shim over :func:`parse_session_kernel`)."""
    parsed = parse_session_kernel(kernel)
    return None if parsed is None else parsed[1]


class EngineThreadBudget:
    """Bounded native-engine thread budget shared across concurrent
    solves. The gRPC servicer runs a thread pool, and every session holds
    its own arena behind its own lock — without a shared budget, two
    concurrent solves each asking for "all hardware threads" oversubscribe
    the host 2x (and N sessions, Nx).

    Each solve acquires a grant of min(requested, available) threads and
    releases it when done. ``acquire`` NEVER BLOCKS: a fully-drained pool
    degrades the grant to a single thread instead of parking the caller —
    blocking would re-create exactly the solve serialization the
    per-session locks removed (the default kernel string requests "all
    hardware threads", so the first solve would drain the pool and every
    concurrent session would queue behind it). The worst case is a
    bounded oversubscription of one thread per concurrent solve (capped
    by the server's worker pool), not Nx total. The native engines are
    bit-identical for every thread count, so a degraded grant can change
    wall-clock but never a result."""

    def __init__(self, total: Optional[int] = None):
        self.total = int(total) if total else (os.cpu_count() or 1)
        self._avail = self.total
        self._lock = make_lock("threadpool")
        # obs plane counters (read by ObsRegistry's budget gauges):
        # cumulative grants, grants smaller than requested (the
        # saturation signal the fleet roadmap gates on), and the lowest
        # availability ever observed
        self.grants = 0
        self.degraded_grants = 0
        self.min_avail = self.total

    def acquire(self, want: int, tenant: str = "-") -> int:
        """Returns the grant size (>= 1, never blocks). ``tenant`` is
        accepted for signature parity with the fleet layer's
        :class:`~protocol_tpu.fleet.admission.FairThreadBudget` (which
        caps grants at the tenant's weighted share); the base budget
        ignores it."""
        want = self.total if want <= 0 else min(int(want), self.total)
        with self._lock:
            grant = max(1, min(want, self._avail))
            self._avail -= grant
            self.grants += 1
            if grant < want:
                self.degraded_grants += 1
            if self._avail < self.min_avail:
                self.min_avail = self._avail
        _tracer.point("budget.grant", want=want, grant=grant)
        return grant

    def release(self, grant: int, tenant: str = "-") -> None:
        with self._lock:
            self._avail += int(grant)

    @property
    def available(self) -> int:
        """Uncommitted thread capacity (negative under the bounded
        oversubscription a contended 1-thread floor grant allows)."""
        with self._lock:
            return self._avail


def _pad_cols(cols: dict[str, np.ndarray], n_real: int) -> dict[str, np.ndarray]:
    """Pad columns to the next pow2 bucket with valid=False rows — the
    same bucketing contract as scheduler_grpc._pad_pow2 (zero fill +
    valid mask), so session solves and unary solves see bit-identical
    padded inputs."""
    if n_real <= 0:
        return dict(cols)
    target = 1 << (n_real - 1).bit_length()
    if target == n_real:
        return dict(cols)
    out = {}
    for name, a in cols.items():
        pad = [(0, target - n_real)] + [(0, 0)] * (a.ndim - 1)
        out[name] = np.pad(a, pad)
    out["valid"] = np.concatenate(
        [np.asarray(cols["valid"], bool)[:n_real],
         np.zeros(target - n_real, bool)]
    )
    return out


def _as_ns(cols: dict[str, np.ndarray]) -> object:
    ns = type("_Cols", (), {})()
    for name, arr in cols.items():
        setattr(ns, name, arr)
    return ns


@dataclass
class SolveSession:
    session_id: str
    fingerprint: str
    weights: object  # CostWeights
    kernel: str
    threads: int
    top_k: int
    p_cols: dict  # padded, wire dtypes
    r_cols: dict
    n_providers: int  # real (unpadded) row counts
    n_tasks: int
    arena: object  # NativeSolveArena
    tick: int = 0
    last_used: float = field(default_factory=time.monotonic)
    lock: threading.Lock = field(default_factory=_session_lock)
    delta_rows_total: int = 0
    # set (under the store lock) when the store lets go of this session —
    # LRU eviction, TTL expiry, drop, or same-id replacement. An in-flight
    # AssignDelta that already looked the session up must REFUSE after
    # seeing this instead of solving against (and advancing the tick of)
    # an arena the store no longer owns: the client's next delta would be
    # refused anyway ("unknown session"), but its shadow columns would
    # have silently diverged from a solve nobody can replay.
    evicted: bool = False
    # shared EngineThreadBudget (None = unbudgeted, use arena.threads)
    budget: object = None
    # flight recorder (trace.recorder.TraceRecorder) when this session
    # claimed the PROTOCOL_TPU_TRACE stream: every APPLIED delta lands
    # its exact wire rows from apply_delta (refused deltas never record)
    trace: object = None
    # delta-stream backpressure (fleet layer): ticks currently inside
    # the servicer for this session (parked on ``lock`` included). The
    # depth check must happen BEFORE parking on the session lock — a
    # client re-sending into a slow session would otherwise stack RPC
    # workers on the lock, which is exactly the queue the bound exists
    # to refuse. Guarded by its own tiny lock so the check never
    # contends with a running solve.
    inflight: int = 0
    inflight_lock: threading.Lock = field(default_factory=_inflight_lock)
    # fleet arena-budget accounting: byte estimate of this session's
    # pinned state (padded columns + candidate structure + duals),
    # computed once at open from rows x dtype widths
    # (fleet.fabric.estimate_arena_bytes) — never re-measured
    arena_bytes: int = 0
    # ---- idempotent-retransmit cache (chaos plane). A delta whose
    # RESPONSE died on the wire (or whose servicer crashed after the
    # flush-before-ack checkpoint) is retransmitted by the client with
    # the same tick: instead of refusing it into a full-snapshot reopen,
    # the servicer matches the retransmit's CRC against the last APPLIED
    # delta and replays the cached answer — the tick is applied exactly
    # once, and the "no tick lost or double-applied" gate rests on this.
    last_delta_crc: int = 0
    last_p4t: object = None  # np.ndarray [n_tasks] i32 after any solve
    # streaming surface (protocol_tpu/stream/): a session opened with
    # stream_mode binds a StreamEngine to its arena — event-typed
    # deltas route through per-event localized repair instead of a full
    # warm solve, with periodic full-solve reconciliation. None = batch
    # session (event-typed deltas are refused "not stream-servable").
    # Mutated only under ``lock``.
    stream: object = None
    # ---- graceful degradation (bounded staleness). When a tick's
    # deadline budget is already burned (lock wait + decode + the EWMA
    # of recent solve walls would overrun it), the servicer serves the
    # PREVIOUS plan with an explicit stale flag instead of starting a
    # solve it cannot finish in time; the streak is hard-bounded by the
    # fleet config (beyond it the solve runs regardless — staleness is
    # a contract, not an escape hatch).
    stale_streak: int = 0
    solve_ewma_ms: float = 0.0

    def enter_tick(self, max_depth: int) -> bool:
        """Claim one queued-tick slot; False = over ``max_depth``
        (refuse with the RESOURCE_EXHAUSTED shape). ``max_depth <= 0``
        disables the bound."""
        with self.inflight_lock:
            if max_depth > 0 and self.inflight >= max_depth:
                return False
            self.inflight += 1
            return True

    def exit_tick(self) -> None:
        with self.inflight_lock:
            self.inflight -= 1

    def solve(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the warm arena over the current columns; returns
        (provider_for_task[T], task_for_provider[P], price[P]) over the
        REAL row counts. With a ``budget`` attached, the solve borrows a
        bounded thread grant so concurrent sessions share the host's
        cores instead of oversubscribing them (results are thread-count
        invariant, so the grant size never changes the matching)."""
        grant = None
        if self.budget is not None:
            # tenant-tagged grant: the fleet's FairThreadBudget caps it
            # at the tenant's weighted share under contention; the base
            # budget ignores the tag (signature parity)
            from protocol_tpu.obs.metrics import tenant_of

            tenant = tenant_of(self.session_id)
            grant = self.budget.acquire(self.threads, tenant)
            self.arena.threads = grant
        try:
            p4t_full = self.arena.solve(
                _as_ns(self.p_cols), _as_ns(self.r_cols), self.weights
            )
        finally:
            if grant is not None:
                self.budget.release(grant, tenant)
        p4t = np.asarray(p4t_full)[: self.n_tasks]
        t4p = np.full(self.n_providers, -1, np.int32)
        seated = np.flatnonzero((p4t >= 0) & (p4t < self.n_providers))
        t4p[p4t[seated]] = seated.astype(np.int32)
        price = np.asarray(self.arena.price)[: self.n_providers]
        return p4t, t4p, price

    def apply_delta(
        self,
        provider_rows: np.ndarray,
        p_delta: dict[str, np.ndarray],
        task_rows: np.ndarray,
        r_delta: dict[str, np.ndarray],
        events: Optional[list] = None,
    ) -> int:
        """Write churned rows into the session columns, copy-on-write per
        column. Returns the number of rows actually applied. Row indices
        are validated against the REAL row space — padding rows are the
        server's own invention and never addressable from the wire.
        ``events`` is the stream meta ([{kind, source, seq}]) an
        event-typed delta carries — recorded into the flight-recorder
        DELTA frame so a captured stream session replays as a stream
        trace (event_from_delta finds its meta), never as a plain
        batch trace."""
        groups = (
            (provider_rows, p_delta, self.p_cols, self.n_providers,
             P_WIRE_DTYPES),
            (task_rows, r_delta, self.r_cols, self.n_tasks, R_WIRE_DTYPES),
        )
        # validate EVERYTHING before the first write: a mid-application
        # raise would leave the session half-mutated with an unadvanced
        # tick — state matching no client's shadow copy anywhere
        for rows, delta, _cols, n_real, spec in groups:
            if rows.size == 0:
                continue
            if rows.min() < 0 or rows.max() >= n_real:
                raise ValueError(
                    f"delta row index out of range [0, {n_real})"
                )
            for name in spec:
                if np.asarray(delta[name]).shape[0] != rows.size:
                    # without this, numpy BROADCASTS a 1-row payload into
                    # every indexed row and the server acks a delta whose
                    # columns silently diverged from the client's shadow
                    # copy — the exact divergence the tick/fingerprint
                    # machinery exists to refuse
                    raise ValueError(
                        f"delta column {name!r} has "
                        f"{np.asarray(delta[name]).shape[0]} rows for "
                        f"{rows.size} row indices"
                    )
        applied = 0
        for rows, delta, cols, _n_real, spec in groups:
            if rows.size == 0:
                continue
            for name in spec:
                new_vals = delta[name]
                if np.array_equal(cols[name][rows], new_vals):
                    continue  # column untouched by this delta
                col = cols[name].copy()
                col[rows] = new_vals
                cols[name] = col
            applied += int(rows.size)
        self.delta_rows_total += applied
        if self.trace is not None:
            from protocol_tpu.trace.recorder import safe as _trace_safe

            # the delta for the tick the caller is about to advance to
            # (callers hold self.lock here, so tick+1 cannot race);
            # empty deltas record too — a no-churn tick still solves,
            # and replay regenerates the tick sequence from these frames
            _trace_safe(
                self.trace.record_session_delta, self.session_id,
                self.tick + 1, provider_rows, p_delta, task_rows, r_delta,
                events,
            )
        return applied


class SessionStore:
    """LRU + TTL registry of :class:`SolveSession`.

    ``on_evict(session, reason)`` is the fleet fabric's accounting hook:
    invoked for EVERY path that lets go of a session (lru / ttl / drop /
    replace / pressure), always AFTER ``evicted`` is set, and always
    under this store's lock — so the callback must touch only leaf state
    (the fabric's budget lock) and never call back into a store."""

    def __init__(
        self,
        max_sessions: int = 8,
        ttl_s: float = 900.0,
        on_evict=None,
    ):
        self.max_sessions = max_sessions
        self.ttl_s = ttl_s
        self._lock = make_lock("shard")
        self._sessions: OrderedDict[str, SolveSession] = OrderedDict()
        self.evictions = 0
        self.expirations = 0
        self._on_evict = on_evict

    def _let_go_locked(self, session: SolveSession, reason: str) -> None:
        session.evicted = True
        _tracer.point(
            "session.evict", session=session.session_id, reason=reason
        )
        if self._on_evict is not None:
            self._on_evict(session, reason)

    def _expire_locked(self) -> None:
        now = time.monotonic()
        dead = [
            sid for sid, s in self._sessions.items()
            if now - s.last_used > self.ttl_s
        ]
        for sid in dead:
            s = self._sessions.pop(sid)
            self.expirations += 1
            self._let_go_locked(s, "ttl")

    def sweep(self) -> int:
        """Deterministic TTL sweep — the fleet layer's hook for
        releasing idle expired sessions' arena memory WITHOUT waiting
        for the next access-path touch (before this, an idle expired
        session pinned its arena until some other call happened to
        enter ``put``/``get``). Returns the number expired."""
        with self._lock:
            before = self.expirations
            self._expire_locked()
            return self.expirations - before

    def put(self, session: SolveSession) -> None:
        with self._lock:
            self._expire_locked()
            replaced = self._sessions.pop(session.session_id, None)
            if replaced is not None:
                self._let_go_locked(replaced, "replace")
            self._sessions[session.session_id] = session
            while len(self._sessions) > self.max_sessions:
                _sid, lru = self._sessions.popitem(last=False)
                self.evictions += 1
                self._let_go_locked(lru, "lru")

    def get(
        self, session_id: str, fingerprint: str
    ) -> tuple[Optional[SolveSession], str]:
        """Look up a session for a delta tick. Returns (session, "") on
        hit or (None, reason) — reason is wire-safe text the client logs."""
        with _tracer.span("session.lookup", session=session_id):
            with self._lock:
                self._expire_locked()
                s = self._sessions.get(session_id)
                if s is None:
                    return None, "unknown session"
                if s.fingerprint != fingerprint:
                    return None, "epoch fingerprint mismatch"
                self._sessions.move_to_end(session_id)
                s.last_used = time.monotonic()
                return s, ""

    def drop(self, session_id: str) -> None:
        with self._lock:
            dropped = self._sessions.pop(session_id, None)
            if dropped is not None:
                self._let_go_locked(dropped, "drop")

    def evict(self, session_id: str, reason: str = "pressure") -> bool:
        """Targeted eviction (the fabric's cross-shard memory-pressure
        path). Same evicted-flag semantics as LRU/TTL: an in-flight
        delta that already looked the session up refuses after seeing
        the flag. False = the session was already gone (lost a race to
        another eviction path — fine, the memory is released either
        way)."""
        with self._lock:
            s = self._sessions.pop(session_id, None)
            if s is None:
                return False
            self.evictions += 1
            self._let_go_locked(s, reason)
            return True

    def lru_candidate(self, exclude=(), tenant=None):
        """(session_id, last_used) of the least-recently-used session —
        the fabric's per-shard input to GLOBAL victim selection. The
        OrderedDict is access-ordered (``get`` moves to end), so the
        first entry is the shard-local LRU. ``tenant`` filters victims
        to one tenant (per-tenant budget pressure)."""
        from protocol_tpu.obs.metrics import tenant_of

        with self._lock:
            for sid, s in self._sessions.items():
                if sid in exclude:
                    continue
                if tenant is not None and tenant_of(sid) != tenant:
                    continue
                return sid, s.last_used
        return None

    def snapshot_sessions(self) -> list:
        """Point-in-time list of the live sessions (drain's checkpoint
        flush walks it; each session is then locked individually — the
        store lock is never held across a flush)."""
        with self._lock:
            return list(self._sessions.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)
