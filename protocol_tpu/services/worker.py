"""Worker agent: provider-side node daemon.

Reference: crates/worker (7,545 LoC; SURVEY.md §2.5, boot call-stack §3.1).
Kept behaviors:

  - system checks -> ComputeSpecs + issue report with minimums
    (checks/hardware/hardware_check.rs:67-95: 4 cores / 8 GB / 1 TB)
  - pool ComputeRequirements gate before starting (cli/command.rs:398-436)
  - provider registration + stake + compute-node registration on the ledger
    (operations/provider.rs, compute_node.rs)
  - signed discovery upload with multi-URL failover + periodic re-upload
    (services/discovery.rs:26-102)
  - invite handling: verify the orchestrator's signed invite, join the pool
    on the ledger from the provider wallet, start heartbeating the invite
    URL (worker/src/p2p/mod.rs:396-497)
  - 10 s signed heartbeat carrying task state + metrics + runtime details;
    the response's current_task drives the runtime
    (operations/heartbeat/service.rs:140-293)
  - task runtime reconcile loop: name = task-{id}-{confighash} so config
    changes force a restart; restart backoff; state mapping
    (docker/service.rs:56-295). The runtime is pluggable: a subprocess
    runtime (dev; containers are orthogonal to this framework's scope) and
    a mock runtime for tests stand where the reference drives dockerd.
  - TaskBridge: unix-socket JSON intake from the running workload — metrics
    -> heartbeat metrics; sha256+flops -> upload request + ledger work
    submission, deduped by sha (docker/taskbridge/bridge.rs:150-419)

Control plane deviation (by design): the reference's libp2p
request-response protocols (Invite / HardwareChallenge / GetTaskLogs /
Restart) are served here as wallet-signed HTTP endpoints on the worker
(/control/*) with the same payloads and authorization (only the pool's
compute-manager key or known validators) — one security scheme across the
whole framework instead of two.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import json
import os
import shutil
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Optional

from aiohttp import web

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.models.heartbeat import TaskDetails
from protocol_tpu.models.node import ComputeRequirements, ComputeSpecs, CpuSpecs, GpuSpecs, Node
from protocol_tpu.models.task import Task, TaskState
from protocol_tpu.security.middleware import validate_signature_middleware
from protocol_tpu.security.signer import sign_request
from protocol_tpu.security.wallet import Wallet
from protocol_tpu.store.kv import KVStore

RESTART_BACKOFF_SECONDS = 10.0  # docker/service.rs:30


class SystemState:
    """Crash-recovery state (reference: worker/src/state/system_state.rs —
    persisted heartbeat endpoint + p2p keypair in the platform data dir,
    enabling `--no-auto-recover`-style resume after restart).

    Persists the orchestrator heartbeat URL and the node wallet key as JSON
    under ``state_dir``; a restarted worker resumes heartbeating without
    waiting for a fresh invite.
    """

    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        self.path = os.path.join(state_dir, "worker_state.json")

    def save(self, orchestrator_url: Optional[str], node_key_hex: str) -> None:
        # the file holds a private key: owner-only permissions throughout
        os.makedirs(self.state_dir, mode=0o700, exist_ok=True)
        tmp = self.path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(
                {
                    "orchestrator_url": orchestrator_url,
                    "node_key_hex": node_key_hex,
                },
                f,
            )
        os.replace(tmp, self.path)  # atomic: a crash never leaves half-state

    def load(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def clear(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------- checks

@dataclass
class Issue:
    level: str  # "critical" | "warning"
    message: str


@dataclass
class IssueReport:
    issues: list[Issue] = field(default_factory=list)

    def add(self, level: str, message: str) -> None:
        self.issues.append(Issue(level, message))

    @property
    def critical(self) -> list[Issue]:
        return [i for i in self.issues if i.level == "critical"]


def detect_compute_specs(
    storage_path: str = "/", probe_accelerator: bool = True
) -> tuple[ComputeSpecs, IssueReport]:
    """Host introspection (checks/hardware/): CPU cores, RAM, disk; TPU/GPU
    detection via the JAX device list when available.

    ``probe_accelerator=False`` skips the jax.devices() call — backend
    initialization can block indefinitely when a remote accelerator plugin
    is unreachable, and control-plane processes must boot regardless.
    """
    report = IssueReport()
    cores = os.cpu_count() or 1
    ram_mb = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    ram_mb = int(line.split()[1]) // 1024
                    break
    except OSError:
        report.add("warning", "could not read /proc/meminfo")
    storage_gb = shutil.disk_usage(storage_path).total // (1024**3)

    # minimums (hardware_check.rs:67-95)
    if cores < 4:
        report.add("warning", f"only {cores} CPU cores (minimum 4)")
    if ram_mb < 8 * 1024:
        report.add("warning", f"only {ram_mb} MB RAM (minimum 8 GB)")
    if storage_gb < 1000:
        report.add("warning", f"only {storage_gb} GB storage (minimum 1 TB)")

    gpu = None
    if probe_accelerator:
        try:  # accelerator presence via jax, the framework's device layer
            import jax

            devs = [d for d in jax.devices() if d.platform != "cpu"]
            if devs:
                gpu = GpuSpecs(count=len(devs), model=devs[0].device_kind)
        except Exception:
            pass

    specs = ComputeSpecs(
        gpu=gpu,
        cpu=CpuSpecs(cores=cores),
        ram_mb=ram_mb,
        storage_gb=storage_gb,
        storage_path=storage_path,
    )
    return specs, report


# ---------------------------------------------------------------- runtime

class TaskRuntime(ABC):
    """Pluggable task executor (the reference's DockerService seam)."""

    @abstractmethod
    async def apply(self, task: Optional[Task], node_address: str) -> None: ...

    @abstractmethod
    def state(self) -> tuple[Optional[str], TaskState, Optional[TaskDetails]]: ...


class MockRuntime(TaskRuntime):
    """Test runtime: tracks the applied task, reports RUNNING."""

    def __init__(self):
        self.current: Optional[Task] = None
        self.applied: list[Optional[str]] = []

    async def apply(self, task, node_address):
        self.current = task
        self.applied.append(task.id if task else None)

    def state(self):
        if self.current is None:
            return None, TaskState.UNKNOWN, None
        return self.current.id, TaskState.RUNNING, TaskDetails(container_status="running")


class SubprocessRuntime(TaskRuntime):
    """Subprocess-based executor: runs ``task.cmd`` with the task's env.

    Reconcile semantics mirror docker/service.rs: a task is identified by
    id + config hash, so an env/cmd change restarts the process; failures
    get RESTART_BACKOFF_SECONDS backoff with a consecutive-failure count.
    """

    def __init__(self, socket_path: Optional[str] = None):
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.current: Optional[Task] = None
        self.current_hash: Optional[str] = None
        self.last_exit: Optional[int] = None
        self.failures = 0
        self.backoff_until = 0.0
        self.socket_path = socket_path
        self.logs: list[str] = []

    async def apply(self, task: Optional[Task], node_address: str) -> None:
        new_hash = task.generate_config_hash() if task else None
        if task and self.current and task.id == self.current.id and new_hash == self.current_hash:
            if self.proc and self.proc.returncode is None:
                return  # already running the right config
            # crashed: restart with backoff (docker/service.rs:160-167)
            if time.monotonic() < self.backoff_until:
                return
        await self._stop()
        self.current, self.current_hash = task, new_hash
        if task is None or not task.cmd:
            return
        env = dict(os.environ)
        env.update(task.env_vars or {})
        env["NODE_ADDRESS"] = node_address  # service.rs:190-201
        env["PRIME_TASK_ID"] = task.id
        if self.socket_path:
            env["SOCKET_PATH"] = self.socket_path
        cmd = list(task.entrypoint or []) + list(task.cmd)
        try:
            self.proc = await asyncio.create_subprocess_exec(
                *cmd,
                env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT,
            )
            asyncio.get_running_loop().create_task(self._pump_logs(self.proc))
        except (OSError, ValueError) as e:
            self.logs.append(f"spawn failed: {e}")
            self.failures += 1
            self.backoff_until = time.monotonic() + RESTART_BACKOFF_SECONDS

    async def _pump_logs(self, proc) -> None:
        while True:
            line = await proc.stdout.readline()
            if not line:
                break
            self.logs.append(line.decode(errors="replace").rstrip())
            if len(self.logs) > 1000:
                del self.logs[:500]
        self.last_exit = await proc.wait()
        if self.last_exit != 0:
            self.failures += 1
            self.backoff_until = time.monotonic() + RESTART_BACKOFF_SECONDS
        else:
            self.failures = 0

    async def _stop(self) -> None:
        if self.proc and self.proc.returncode is None:
            self.proc.terminate()
            try:
                await asyncio.wait_for(self.proc.wait(), timeout=5)
            except asyncio.TimeoutError:
                self.proc.kill()
        self.proc = None

    def state(self):
        """Process state -> TaskState (docker/service.rs:267-281)."""
        if self.current is None:
            return None, TaskState.UNKNOWN, None
        if self.proc is None:
            st = TaskState.FAILED if self.failures else TaskState.PENDING
            return self.current.id, st, TaskDetails(exit_code=self.last_exit)
        if self.proc.returncode is None:
            return self.current.id, TaskState.RUNNING, TaskDetails(
                container_id=str(self.proc.pid), container_status="running"
            )
        st = TaskState.COMPLETED if self.proc.returncode == 0 else TaskState.FAILED
        return self.current.id, st, TaskDetails(exit_code=self.proc.returncode)


# ---------------------------------------------------------------- bridge

class TaskBridge:
    """Unix-socket JSON intake from the running workload
    (docker/taskbridge/bridge.rs). Messages, newline-or-concatenated JSON:
      {"task_id": ..., "<label>": <float>, ...}          -> metrics
      {"output": {"sha256": ..., "output_flops": N,
                  "file_name"/"save_path": ...}}          -> work submission
    """

    def __init__(self, socket_path: str, agent: "WorkerAgent"):
        self.socket_path = socket_path
        self.agent = agent
        self.server: Optional[asyncio.AbstractServer] = None
        self.seen_shas: set[str] = set()  # dedup (bridge.rs:150-156)

    async def start(self) -> None:
        os.makedirs(os.path.dirname(self.socket_path), exist_ok=True)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self.server = await asyncio.start_unix_server(self._handle, self.socket_path)
        os.chmod(self.socket_path, 0o666)

    async def stop(self) -> None:
        if self.server:
            self.server.close()
            await self.server.wait_closed()

    async def _handle(self, reader, writer) -> None:
        # stream parser for concatenated JSON objects (json_helper.rs)
        buf = ""
        decoder = json.JSONDecoder()
        while True:
            chunk = await reader.read(65536)
            if not chunk:
                break
            buf += chunk.decode(errors="replace")
            while True:
                s = buf.lstrip()
                if not s:
                    buf = ""
                    break
                try:
                    obj, end = decoder.raw_decode(s)
                except json.JSONDecodeError:
                    buf = s  # incomplete object: wait for more bytes
                    break
                await self._dispatch(obj)
                buf = s[end:]
        writer.close()

    async def _dispatch(self, obj: dict) -> None:
        if not isinstance(obj, dict):
            return
        if "output" in obj and isinstance(obj["output"], dict):
            out = obj["output"]
            sha = out.get("sha256")
            if sha and sha not in self.seen_shas:
                self.seen_shas.add(sha)
                # save_path names a file the workload wrote: read and ship
                # the bytes through the signed-URL path (the reference's
                # file_handler.rs:21-118 watches the output dir the same
                # way). Integrity-gated: bytes that don't hash to the
                # claimed sha are not uploaded — the work submission then
                # follows the bodyless best-effort path unchanged.
                data = None
                save_path = out.get("save_path")
                if save_path:

                    def _read_verified(path=save_path, want=sha):
                        # runs off the event loop: reading + hashing up
                        # to 100 MB synchronously would stall heartbeats
                        # and the control server for the whole window
                        if os.path.getsize(path) > 100 * 1024 * 1024:
                            return None, "exceeds the 100 MB upload cap"
                        with open(path, "rb") as f:
                            raw = f.read()
                        if hashlib.sha256(raw).hexdigest() != want:
                            return None, "does not hash to the claimed sha"
                        return raw, None

                    try:
                        data, why = await asyncio.to_thread(_read_verified)
                    except OSError as e:
                        data, why = None, f"unreadable: {e}"
                    if data is None and why:
                        logging.getLogger(__name__).warning(
                            "bridge output %s %s; uploading nothing",
                            save_path, why,
                        )
                await self.agent.submit_output(
                    sha=sha,
                    flops=int(out.get("output_flops", 0)),
                    file_name=out.get("file_name") or out.get("save_path") or sha,
                    data=data,
                    # colocated workloads share ONE bridge socket: the
                    # message's own task id (either placement) keeps an
                    # extra task's artifact from being attributed to the
                    # primary; absent -> current_task (legacy workloads)
                    task_id=out.get("task_id") or obj.get("task_id"),
                )
            return
        task_id = obj.get("task_id")
        if task_id:
            for key, value in obj.items():
                if key == "task_id":
                    continue
                try:
                    self.agent.metrics[(str(task_id), str(key))] = float(value)
                except (TypeError, ValueError):
                    continue


# ---------------------------------------------------------------- agent

class WorkerAgent:
    def __init__(
        self,
        provider_wallet: Wallet,
        node_wallet: Wallet,
        ledger: Ledger,
        pool_id: int,
        runtime: Optional[TaskRuntime] = None,
        compute_specs: Optional[ComputeSpecs] = None,
        ip_address: str = "127.0.0.1",
        port: int = 8091,
        http=None,  # aiohttp.ClientSession-compatible (tests inject)
        known_orchestrators: Optional[list[str]] = None,
        known_validators: Optional[list[str]] = None,
        state: Optional[SystemState] = None,
        auto_recover: bool = True,
        ipfs=None,  # utils.ipfs.IpfsMirror: best-effort artifact mirroring
        price: Optional[float] = None,
        control_scheme: str = "http",  # "https" when the control app serves TLS
        public_http=None,  # session for EXTERNAL signed-URL PUTs (GCS/S3).
        # None = reuse ``http`` (tests, plaintext devnets); "lazy" = build a
        # system-trust session on first external PUT (serve.py) so a pinned
        # deployment CA can't break GCS uploads and a worker that never
        # uploads never holds the extra session
        runtime_factory=None,  # (slot: str) -> TaskRuntime: enables
        # CONCURRENT execution of colocated assignments (heartbeat
        # assigned_tasks, ladder #5) — one runtime per extra task, slot
        # is a stable 8-hex discriminator the DockerRuntime uses to keep
        # sibling reconciles from sweeping each other's containers.
        # None = legacy single-task behavior (extras ignored)
    ):
        self.ipfs = ipfs
        # advertised ask price (cost units/hour), carried through discovery
        # into the orchestrator's batch-matcher cost term
        self.price = price
        if control_scheme not in ("http", "https"):
            raise ValueError(f"control_scheme must be http/https, got {control_scheme!r}")
        self.control_scheme = control_scheme
        self.provider_wallet = provider_wallet
        self.node_wallet = node_wallet
        self.ledger = ledger
        self.pool_id = pool_id
        self.runtime = runtime or MockRuntime()
        self.compute_specs = compute_specs
        self.ip_address = ip_address
        self.port = port
        self.http = http
        self.public_http = public_http
        self.kv = KVStore()
        self.metrics: dict[tuple[str, str], float] = {}
        self.orchestrator_url: Optional[str] = None
        self.current_task: Optional[Task] = None
        self.heartbeat_active = False
        self._discovery_rejections: set[tuple] = set()
        self.runtime_factory = runtime_factory
        self.extra_runtimes: dict[str, TaskRuntime] = {}  # task id -> runtime
        self.known_orchestrators = [a.lower() for a in (known_orchestrators or [])]
        self.known_validators = [a.lower() for a in (known_validators or [])]
        self.p2p_id = f"worker-{node_wallet.address[:10]}"
        # chain drift monitor state (stake_monitor_once)
        self._chain_state: dict[str, bool] = {}
        self._chain_error = False
        self.chain_alarms: list[str] = []
        self.deregistered = False
        self.state = state
        if state is not None and auto_recover:
            # crash recovery (cli/command.rs:832-835): resume heartbeating
            # the persisted endpoint without waiting for a new invite —
            # but only when the persisted identity IS this wallet; stale
            # state from another identity would leave the worker signing
            # beats the orchestrator never invited
            saved = state.load()
            if (
                saved
                and saved.get("orchestrator_url")
                and saved.get("node_key_hex") == node_wallet.private_key_hex()
            ):
                self.orchestrator_url = saved["orchestrator_url"]
                self.heartbeat_active = True

    # ----- boot (cli/command.rs:194-848) -----

    def check_pool_requirements(self) -> bool:
        pool = self.ledger.get_pool_info(self.pool_id)
        if not pool.pool_data_uri:
            return True
        try:
            reqs = ComputeRequirements.parse(pool.pool_data_uri)
        except ValueError:
            return True
        return self.compute_specs is not None and self.compute_specs.meets(reqs)

    def register_on_ledger(self) -> None:
        """Provider registration + stake + node registration
        (operations/provider.rs:175-331, compute_node.rs:32-115)."""
        stake = self.ledger.calculate_stake(1)
        if not self.ledger.provider_exists(self.provider_wallet.address):
            self.ledger.register_provider(self.provider_wallet.address, stake)
        if not self.ledger.node_exists(self.node_wallet.address):
            required = self.ledger.calculate_stake(
                self.ledger.get_provider_total_compute(self.provider_wallet.address) + 1
            )
            current = self.ledger.get_stake(self.provider_wallet.address)
            if current < required:
                self.ledger.increase_stake(
                    self.provider_wallet.address, required - current
                )
            self.ledger.add_compute_node(
                self.provider_wallet.address, self.node_wallet.address
            )

    def discovery_node_payload(self) -> dict:
        node = Node(
            id=self.node_wallet.address,
            provider_address=self.provider_wallet.address,
            ip_address=self.ip_address,
            port=self.port,
            compute_pool_id=self.pool_id,
            compute_specs=self.compute_specs,
            worker_p2p_id=self.p2p_id,
            worker_p2p_addresses=[
                f"{self.control_scheme}://{self.ip_address}:{self.port}/control"
            ],
            price=self.price,
        )
        return node.to_dict()

    async def upload_to_discovery(self, urls: list[str]) -> bool:
        """Signed PUT /api/nodes with multi-URL failover
        (services/discovery.rs:26-102). Rejections are logged once per
        distinct reason: a gate rejection (per-IP cap, whitelist, pool
        membership) repeats every beat forever, and a silently-invisible
        worker is an operator-hostile failure mode (a soak spent an hour
        on exactly this)."""
        payload = self.discovery_node_payload()
        for url in urls:
            headers, body = sign_request("/api/nodes", self.node_wallet, payload)
            try:
                async with self.http.put(
                    f"{url}/api/nodes", json=body, headers=headers
                ) as resp:
                    if resp.status == 200:
                        return True
                    # dedup on (url, status) only: bodies can carry
                    # per-request noise (timestamps, request ids) that
                    # would defeat the dedup AND grow the set forever on
                    # the every-beat retry loop
                    key = (url, resp.status)
                    if key not in self._discovery_rejections:
                        self._discovery_rejections.add(key)
                        logging.getLogger(__name__).warning(
                            "discovery %s rejected registration (%d): %s",
                            url, resp.status, (await resp.text())[:200],
                        )
            except Exception:
                continue
        return False

    # ----- control-plane HTTP (the libp2p-equivalent surface) -----

    def make_control_app(self) -> web.Application:
        allowed = set(self.known_orchestrators + self.known_validators)
        if not allowed:
            # Fail closed: with no configured orchestrator/validator
            # allowlist, derive it from the substrate exactly like the
            # reference (cli/command.rs:717-734): pool creator + compute
            # manager + every wallet holding the validator role
            # (prime_network.get_validator_role) — never "any valid
            # signature". If the lookup fails the surface rejects all.
            try:
                pool = self.ledger.get_pool_info(self.pool_id)
                allowed = {pool.creator, pool.compute_manager_key}
                allowed.update(self.ledger.get_validator_role())
            except Exception:
                allowed = set()
        app = web.Application(
            middlewares=[
                validate_signature_middleware(
                    self.kv, ["/control"], allowed_addresses=allowed
                )
            ]
        )
        app.router.add_post("/control/invite", self.handle_invite)
        app.router.add_post("/control/challenge", self.handle_challenge)
        app.router.add_get("/control/logs", self.handle_logs)
        app.router.add_post("/control/restart", self.handle_restart)
        return app

    async def handle_invite(self, request: web.Request) -> web.Response:
        """Verify + accept a pool invite (worker/src/p2p/mod.rs:396-497):
        join the pool on the ledger from the provider wallet, then start
        heartbeating the invite URL."""
        body = request.get("auth_body") or {}
        try:
            pool_id = int(body["pool_id"])
            nonce = str(body["invite_nonce"])
            expiration = float(body["expiration"])
            signature = str(body["invite_signature"])
            heartbeat_url = str(body["heartbeat_url"])
        except (KeyError, ValueError):
            return web.json_response(
                {"success": False, "error": "malformed invite"}, status=400
            )
        if pool_id != self.pool_id:
            return web.json_response(
                {"success": False, "error": "wrong pool"}, status=400
            )
        try:
            self.ledger.join_compute_pool(
                pool_id,
                self.provider_wallet.address,
                self.node_wallet.address,
                nonce,
                expiration,
                signature,
            )
        except LedgerError as e:
            if "already in a pool" not in str(e):
                return web.json_response(
                    {"success": False, "error": str(e)}, status=400
                )
        self.orchestrator_url = heartbeat_url
        self.heartbeat_active = True
        if self.state is not None:
            self.state.save(heartbeat_url, self.node_wallet.private_key_hex())
        return web.json_response({"success": True})

    async def handle_challenge(self, request: web.Request) -> web.Response:
        """Hardware challenge: dense matmul computed on this worker's
        accelerator via jnp (the reference's nalgebra calc_matrix,
        p2p/src/message/hardware_challenge.rs:74-89, made device-native)."""
        body = request.get("auth_body") or {}
        import numpy as np

        from protocol_tpu.utils import fixedf64

        fixed_wire = "matrix_a_fixed" in body
        try:
            if fixed_wire:
                # FixedF64 wire (utils/fixedf64.py — a deliberate Q31.32
                # deviation from hardware_challenge.rs's decimal-string
                # wire, equivalent determinism; see PARITY.md): decode to
                # the bit-exact float64s the validator encoded
                a = fixedf64.decode_array(body["matrix_a_fixed"]).astype(np.float32)
                b = fixedf64.decode_array(body["matrix_b_fixed"]).astype(np.float32)
            else:  # legacy float-JSON wire
                a = np.asarray(body["matrix_a"], np.float32)
                b = np.asarray(body["matrix_b"], np.float32)
        except (KeyError, ValueError, TypeError):
            return web.json_response(
                {"success": False, "error": "missing matrices"}, status=400
            )

        def compute():
            # device work off the event loop: jax calls are synchronous and
            # must not stall the control plane if the accelerator is slow
            import jax.numpy as jnp

            return np.asarray(jnp.asarray(a) @ jnp.asarray(b))

        result = await asyncio.to_thread(compute)
        if fixed_wire:
            try:
                encoded = fixedf64.encode_array(result)
            except ValueError:
                # adversarially-huge (but decodable) inputs can overflow
                # the float32 matmul to inf/nan — a clean rejection, not
                # a 500
                return web.json_response(
                    {"success": False, "error": "non-finite result"},
                    status=400,
                )
            return web.json_response({"success": True, "result_fixed": encoded})
        return web.json_response({"success": True, "result": result.tolist()})

    async def handle_logs(self, request: web.Request) -> web.Response:
        fetch = getattr(self.runtime, "get_logs", None)
        logs = await fetch() if fetch is not None else getattr(self.runtime, "logs", [])
        return web.json_response({"success": True, "logs": logs[-100:]})

    async def handle_restart(self, request: web.Request) -> web.Response:
        restart = getattr(self.runtime, "restart_task", None)
        if restart is not None:
            # runtimes with an in-place restart (DockerRuntime -> docker
            # restart, service.rs:332-343) keep the container identity and
            # avoid the remove->backoff window a stop/start cycle would hit
            await restart()
        elif self.current_task is not None:
            await self.runtime.apply(None, self.node_wallet.address)
            await self.runtime.apply(self.current_task, self.node_wallet.address)
        return web.json_response({"success": True})

    # ----- heartbeat (operations/heartbeat/service.rs:140-293) -----

    # ----- stake / chain-event monitor (provider.rs:47-147,
    # compute_node.rs:32-115) -----

    def stake_monitor_once(self) -> list[str]:
        """One tick of the reference's continuous provider monitors:
        re-check stake sufficiency, whitelist status, node registration,
        and pool membership. Returns the NEW alarms (True->False
        transitions since the previous tick — levels alone would re-alarm
        every tick), accumulates them on ``self.chain_alarms``, and stops
        heartbeating when the node itself was deregistered on-chain.

        The reference registers once at boot and then watches drift in
        dedicated loops; round 2 of this framework only did the former, so
        a mid-run slash went unnoticed by the worker (VERDICT r2 item 8).
        """
        state: dict[str, bool] = {}
        alarms: list[str] = []
        provider = self.provider_wallet.address
        node = self.node_wallet.address
        try:
            units = max(self.ledger.get_provider_total_compute(provider), 1)
            required = self.ledger.calculate_stake(units)
            current = self.ledger.get_stake(provider)
            state["stake_sufficient"] = current >= required
            state["whitelisted"] = self.ledger.is_provider_whitelisted(provider)
            state["node_registered"] = self.ledger.node_exists(node)
            state["in_pool"] = self.ledger.is_node_in_pool(self.pool_id, node)
        except Exception as e:
            # transition-deduped like the drift alarms: a weekend-long
            # ledger outage must not grow chain_alarms unboundedly
            if not self._chain_error:
                self._chain_error = True
                alarms.append(f"chain monitor error: {e}")
                self._record_alarms(alarms)
            return alarms
        self._chain_error = False

        detail = {
            "stake_sufficient": (
                f"stake {current} below required {required} "
                "(slashed or reclaimed?)"
            ),
            "whitelisted": "provider whitelist revoked",
            "node_registered": "compute node deregistered on-chain",
            "in_pool": "node no longer in pool (ejected?)",
        }
        prev = self._chain_state
        if not prev:
            # first tick establishes the baseline: a worker that boots
            # before its invite is legitimately not in a pool yet — only
            # True -> False TRANSITIONS are drift
            self._chain_state = state
            return []
        for key, msg in detail.items():
            if prev.get(key, True) and not state[key]:
                alarms.append(msg)
        self._chain_state = state
        if alarms:
            self._record_alarms(alarms)
        if prev.get("node_registered", True) and not state["node_registered"]:
            # a deregistered node signing heartbeats would just be rejected
            # by the orchestrator's validator — stop cleanly instead (the
            # serve loop exits on this flag)
            self.heartbeat_active = False
            self.deregistered = True
        return alarms

    def _record_alarms(self, alarms: list[str]) -> None:
        for a in alarms:
            logging.getLogger(__name__).warning("worker chain alarm: %s", a)
        self.chain_alarms.extend(alarms)
        del self.chain_alarms[:-100]  # bounded history

    def _host_load(self) -> float:
        """Self-reported host utilization 0..1 (1-min loadavg over cores),
        shipped with every heartbeat. External to the pool's own assignment
        state on purpose: the matcher's load cost term must not feed back
        into the solve that produces it."""
        try:
            return min(os.getloadavg()[0] / max(os.cpu_count() or 1, 1), 1.0)
        except OSError:
            return 0.0

    def _collect_metrics(self) -> list[dict]:
        return [
            {"key": {"task_id": tid, "label": label}, "value": value}
            for (tid, label), value in self.metrics.items()
        ]

    async def heartbeat_once(self) -> Optional[Task]:
        if not self.heartbeat_active or not self.orchestrator_url:
            return None
        task_id, task_state, details = self.runtime.state()
        payload = {
            "address": self.node_wallet.address,
            "task_id": task_id,
            "task_state": task_state.value if task_state else None,
            "metrics": self._collect_metrics(),
            "version": "0.1.0",
            "timestamp": time.time(),
            "p2p_id": self.p2p_id,
            "p2p_addresses": [
                f"{self.control_scheme}://{self.ip_address}:{self.port}/control"
            ],
            "task_details": details.to_dict() if details else None,
            "load": self._host_load(),
        }
        if self.extra_runtimes:
            # colocated extras report alongside the primary task (additive
            # field; the orchestrator's FSM keys off the primary)
            states: dict[str, Optional[str]] = {}
            for tid, rt in self.extra_runtimes.items():
                _tid, st, _details = rt.state()
                states[tid] = st.value if st else None
            payload["extra_task_states"] = states
        headers, body = sign_request("/heartbeat", self.node_wallet, payload)
        try:
            async with self.http.post(
                f"{self.orchestrator_url}/heartbeat", json=body, headers=headers
            ) as resp:
                if resp.status != 200:
                    return None
                data = await resp.json()
        except Exception:
            return None

        body_data = data.get("data") or {}
        task_dict = body_data.get("current_task")
        new_task = Task.from_dict(task_dict) if task_dict else None
        old_id = self.current_task.id if self.current_task else None
        if (new_task.id if new_task else None) != old_id:
            # metrics reset on task switch (:267-280) — but ONLY the
            # departing primary's entries: colocated extras are still
            # running and their queued samples must survive the swap
            for key in [k for k in self.metrics if k[0] == old_id]:
                del self.metrics[key]
        self.current_task = new_task
        await self.runtime.apply(new_task, self.node_wallet.address)
        if self.runtime_factory is not None:
            # colocated extras (ladder #5): every assigned task beyond
            # the primary runs CONCURRENTLY in its own runtime; without a
            # factory, legacy single-task behavior (extras ignored)
            primary_id = new_task.id if new_task else None
            extras = [
                Task.from_dict(d)
                for d in body_data.get("assigned_tasks") or []
                if d.get("id") != primary_id
            ]
            await self._apply_extra_tasks(extras)
        return new_task

    async def _apply_extra_tasks(self, extras: list[Task]) -> None:
        """Reconcile the per-task extra runtimes against the assignment:
        new colocated tasks get a fresh runtime, departed ones are stopped
        and their runtime dropped (same apply(None) semantics the primary
        runtime uses for task switches)."""
        want = {t.id: t for t in extras}
        for tid in [t for t in self.extra_runtimes if t not in want]:
            rt = self.extra_runtimes.pop(tid)
            try:
                await rt.apply(None, self.node_wallet.address)
            except Exception:
                logging.getLogger(__name__).exception(
                    "stopping colocated task %s failed", tid
                )
        for tid, task in want.items():
            rt = self.extra_runtimes.get(tid)
            if rt is None:
                slot = hashlib.sha256(tid.encode()).hexdigest()[:8]
                rt = self.extra_runtimes[tid] = self.runtime_factory(slot)
            try:
                await rt.apply(task, self.node_wallet.address)
            except Exception:
                logging.getLogger(__name__).exception(
                    "applying colocated task %s failed", tid
                )

    # ----- bridge output -> upload + work submission -----

    def _upload_session(self, url: str):
        """Pick the trust root by the signed URL's DESTINATION: an
        orchestrator-origin URL (LocalDirStorageProvider's /storage/upload
        route) is a control-plane peer behind the pinned CA, while GCS/S3
        signed URLs are public hosts under system trust — one session
        cannot verify both."""
        if self.orchestrator_url:
            from urllib.parse import urlsplit

            # compare scheme://host:port, not a raw string prefix: an
            # orchestrator at https://orch:80 must not capture
            # https://orch:8090/... (which is a different, public origin).
            # Ports normalized so an explicit :443/:80 matches the default.
            def origin(s):
                u = urlsplit(s)
                default = {"https": 443, "http": 80}.get(u.scheme)
                return (u.scheme, u.hostname, u.port or default)

            if origin(self.orchestrator_url) == origin(url):
                return self.http
        if self.public_http == "lazy":
            from protocol_tpu.utils.tls import public_client_session

            self.public_http = public_client_session()
        return self.public_http if self.public_http is not None else self.http

    async def submit_output(
        self,
        sha: str,
        flops: int,
        file_name: str,
        data: Optional[bytes] = None,
        max_retries: int = 5,
        task_id: Optional[str] = None,
    ) -> bool:
        """Upload the artifact then submit the work key on the ledger
        (docker/taskbridge/file_handler.rs:21-118): request a signed URL
        from the orchestrator with exponential-backoff retries, PUT the
        bytes through it, then submitWork(sha, flops). With no ``data``
        the URL request is best-effort (the workload may upload out of
        band) and the work is submitted regardless."""
        if data is not None and (not self.orchestrator_url or self.http is None):
            return False  # nowhere to upload: no artifact -> no work claim
        if self.orchestrator_url and self.http is not None:
            payload = {
                "file_name": file_name,
                "file_size": len(data) if data is not None else 0,
                "file_type": "application/octet-stream",
                "sha256": sha,
                "task_id": task_id
                or (self.current_task.id if self.current_task else None),
            }

            class _Fatal(Exception):
                """Deterministic 4xx: retrying re-signs the same doomed
                request (and 429 retries dig the rate-limit hole deeper)."""

            for attempt in range(max_retries):
                try:
                    headers, body = sign_request(
                        "/storage/request-upload", self.node_wallet, payload
                    )
                    async with self.http.post(
                        f"{self.orchestrator_url}/storage/request-upload",
                        json=body,
                        headers=headers,
                    ) as resp:
                        if 400 <= resp.status < 500:
                            raise _Fatal(f"request-upload {resp.status}")
                        if resp.status != 200:
                            raise RuntimeError(
                                f"request-upload {resp.status}"
                            )
                        url = (await resp.json())["data"]["signed_url"]
                    if data is not None:
                        async with self._upload_session(url).put(
                            url,
                            data=data,
                            headers={"Content-Length": str(len(data))},
                        ) as up:
                            if 400 <= up.status < 500 and up.status not in (408, 429):
                                raise _Fatal(f"upload {up.status}")
                            if up.status not in (200, 201):
                                raise RuntimeError(f"upload {up.status}")
                        if self.ipfs is not None:
                            # best-effort mirror, never blocks the primary
                            # path (file_handler.rs:109-118)
                            await self.ipfs.add(data, file_name=file_name)
                    break
                except _Fatal:
                    if data is not None:
                        return False  # no artifact -> no work claim
                    break  # bodyless legacy path stays best-effort
                except Exception:
                    if attempt == max_retries - 1:
                        if data is not None:
                            return False
                        break
                    await asyncio.sleep(min(0.1 * 2**attempt, 2.0))
        try:
            self.ledger.submit_work(self.pool_id, self.node_wallet.address, sha, flops)
            return True
        except LedgerError:
            return False
