"""Scheduler: the per-heartbeat task-for-node resolution.

Reference: crates/orchestrator/src/scheduler/mod.rs —
``get_task_for_node`` (:26-74) fetches ALL tasks, runs the plugin filter
chain, picks the first surviving task, and expands ``${TASK_ID}`` /
``${NODE_ADDRESS}`` into env vars, cmd, and volume mounts. The default chain
holds the newest-task plugin (:16-18).

This implementation keeps that exact surface (it is the parity oracle the
TPU batch matcher is tested against) but the backend is pluggable: when a
``TpuBatchMatcher`` is attached, per-node resolution is served from the
latest batch assignment computed on the accelerator, falling back to the
greedy chain for nodes the batch didn't cover.
"""

from __future__ import annotations

import copy
from typing import Optional, Protocol, TYPE_CHECKING

from protocol_tpu.models.task import Task
from protocol_tpu.store.context import StoreContext
from protocol_tpu.store.domains.node_store import OrchestratorNode

if TYPE_CHECKING:
    from protocol_tpu.sched.tpu_backend import TpuBatchMatcher


class SchedulerPlugin(Protocol):
    """Filter-chain plugin (reference plugins/mod.rs:61-78 enum dispatch)."""

    def filter_tasks(
        self, tasks: list[Task], node: OrchestratorNode
    ) -> list[Task]: ...


class NewestTaskPlugin:
    """Sort newest-first, pass everything through
    (reference plugins/newest_task/mod.rs)."""

    def filter_tasks(self, tasks: list[Task], node: OrchestratorNode) -> list[Task]:
        return sorted(tasks, key=lambda t: t.created_at, reverse=True)


def expand_task_for_node(task: Task, node_address: str) -> Task:
    """${TASK_ID} / ${NODE_ADDRESS} expansion into env/cmd/volumes
    (scheduler/mod.rs:40-70, task.rs replace_labels)."""
    t = copy.deepcopy(task)

    def sub(s: str) -> str:
        return s.replace("${TASK_ID}", t.id).replace("${NODE_ADDRESS}", node_address)

    if t.env_vars:
        t.env_vars = {k: sub(v) for k, v in t.env_vars.items()}
    if t.cmd:
        t.cmd = [sub(c) for c in t.cmd]
    if t.entrypoint:
        t.entrypoint = [sub(c) for c in t.entrypoint]
    if t.volume_mounts:
        t.volume_mounts = [vm.replace_labels(t.id, node_address) for vm in t.volume_mounts]
    return t


class Scheduler:
    def __init__(
        self,
        store: StoreContext,
        plugins: Optional[list[SchedulerPlugin]] = None,
        batch_matcher: Optional["TpuBatchMatcher"] = None,
    ):
        self.store = store
        self.plugins: list[SchedulerPlugin] = (
            plugins if plugins is not None else [NewestTaskPlugin()]
        )
        self.batch_matcher = batch_matcher

    def get_task_for_node(self, node_address: str) -> Optional[Task]:
        node = self.store.node_store.get_node(node_address)
        if node is None:
            return None

        if self.batch_matcher is not None:
            # Composed gang scheduling (SURVEY §7 hard part 5): when a
            # groups plugin rides alongside the matcher, grouped nodes
            # resolve through the plugin's race-safe group-task machinery
            # (whose selection the matcher ranks via its task_ranker hook);
            # ungrouped nodes fall through to the individual batch solve,
            # which excludes topology-restricted tasks and grouped nodes.
            gp = next(
                (
                    p
                    for p in self.plugins
                    if hasattr(p, "group_for_node") and hasattr(p, "task_ranker")
                ),
                None,
            )
            if gp is not None:
                group = gp.group_for_node(node_address)
                if group is not None:
                    tasks = self.store.task_store.get_all_tasks()
                    filtered = gp.filter_tasks(tasks, node)
                    if not filtered:
                        return None
                    return expand_task_for_node(filtered[0], node_address)

            task, covered = self.batch_matcher.lookup(node)
            if not covered:
                # A node the last solve never considered (e.g. it just became
                # schedulable): request a re-solve and look again. The matcher
                # throttles, so at worst this node waits one heartbeat — the
                # reference reschedules on a 10 s beat anyway. There is NO
                # greedy fallthrough here: it would bypass replica bounds and
                # compute-requirement gates.
                self.batch_matcher.mark_dirty()
                task, covered = self.batch_matcher.lookup(node)
            if task is None:
                return None
            return expand_task_for_node(task, node_address)

        tasks = self.store.task_store.get_all_tasks()
        for plugin in self.plugins:
            tasks = plugin.filter_tasks(tasks, node)
            if not tasks:
                return None
        return expand_task_for_node(tasks[0], node_address)

    def get_tasks_for_node(self, node_address: str) -> list[Task]:
        """Multi-task resolution: colocated nodes (ladder #5 capacity
        sharing, TpuBatchMatcher phase 0.5) hold SEVERAL tasks
        concurrently; everyone else gets a one-element list. The first
        element equals ``get_task_for_node``'s answer from the same
        solve (best-effort under a concurrent re-solve)."""
        first = self.get_task_for_node(node_address)
        if first is None:
            return []
        if self.batch_matcher is None:
            return [first]
        # plain dict read — get_task_for_node above already refreshed and
        # resolved this node; no second lookup on the heartbeat hot path
        tids = self.batch_matcher.assigned_task_ids(node_address)
        if len(tids) <= 1:
            return [first]
        found = (self.store.task_store.get_task(t) for t in tids)
        return [
            expand_task_for_node(t, node_address) for t in found if t is not None
        ]
