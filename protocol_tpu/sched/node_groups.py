"""Node groups: gang scheduling / topologies.

Reference: crates/orchestrator/src/plugins/node_groups/ (1,708 LoC) — the
reference's mechanism for multi-node workloads. Behaviors kept:

- ``NodeGroupConfiguration{name, min_group_size, max_group_size,
  compute_requirements}`` (mod.rs:30-37), configs sorted larger-min-first
  then more-specific-first (mod.rs:150-164).
- Store schema: group blob ``node_group:{id}``, ``node_to_group`` hash,
  ``group_task:{id}`` (SET-NX race-safe assignment, mod.rs:471-476),
  groups index set, enabled-configs set (mod.rs:25-28, 1328-1346).
- Management tick: form new groups from healthy+p2p+unassigned nodes with
  Haversine proximity seeding (mod.rs:478-628, 217-255), then merge solo
  groups (mod.rs:631-860) under a task-switching policy.
- Task observers: creating a task enables the topologies it allows;
  deleting it dissolves that task's groups and disables empty topologies
  (mod.rs:1224-1326).
- Scheduler-side filter for grouped nodes with dissolved-group recovery and
  ``${GROUP_ID}/${GROUP_INDEX}/${GROUP_SIZE}/${NEXT_P2P_ADDRESS}(ring)/
  ${TOTAL_UPLOAD_COUNT}/${LAST_FILE_IDX}`` expansion
  (scheduler_impl.rs:11-210). The ring wiring is what distributed workloads
  (e.g. ring-allreduce training) consume.

TPU-first deviation: per-config node eligibility is not a per-node string
walk — all (node, config) pairs are evaluated in ONE batched compat_mask
call on the accelerator (the same kernel the batch matcher uses), and
proximity ordering uses the vectorized haversine. Only the final greedy
fill (group sizes are small) stays on host.
"""

from __future__ import annotations

import copy
import enum
import json
import random
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from protocol_tpu.models.node import ComputeRequirements
from protocol_tpu.models.task import Task
from protocol_tpu.ops.encoding import FeatureEncoder, compat_mask
from protocol_tpu.store.context import StoreContext
from protocol_tpu.store.domains.node_store import NodeStatus, OrchestratorNode

GROUP_KEY = "node_group:{}"
NODE_TO_GROUP = "node_to_group"
GROUP_TASK_KEY = "group_task:{}"
GROUPS_INDEX = "orchestrator:groups_index"
ENABLED_CONFIGS = "available_node_group_configs"
UPLOAD_COUNTER_KEY = "upload:{}:{}:{}"  # addr, group, file


@dataclass
class NodeGroupConfiguration:
    name: str
    min_group_size: int
    max_group_size: int
    compute_requirements: Optional[str] = None  # requirements DSL

    def parsed_requirements(self) -> ComputeRequirements:
        if self.compute_requirements:
            return ComputeRequirements.parse(self.compute_requirements)
        return ComputeRequirements()

    def specificity(self) -> int:
        """Constraint count for the more-specific-first sort."""
        r = self.parsed_requirements()
        n = len(r.gpu)
        n += sum(x is not None for x in (r.cpu, r.ram_mb, r.storage_gb))
        return n

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "min_group_size": self.min_group_size,
            "max_group_size": self.max_group_size,
            "compute_requirements": self.compute_requirements,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NodeGroupConfiguration":
        return cls(
            name=d["name"],
            min_group_size=int(d["min_group_size"]),
            max_group_size=int(d["max_group_size"]),
            compute_requirements=d.get("compute_requirements"),
        )


@dataclass
class NodeGroup:
    id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    configuration_name: str = ""
    nodes: list[str] = field(default_factory=list)  # ordered: index = rank
    created_at: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "configuration_name": self.configuration_name,
            "nodes": self.nodes,
            "created_at": self.created_at,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NodeGroup":
        return cls(
            id=d["id"],
            configuration_name=d["configuration_name"],
            nodes=list(d["nodes"]),
            created_at=float(d.get("created_at", 0.0)),
        )


class TaskSwitchingPolicy(str, enum.Enum):
    """Whether solo-group merging may move a node off its current task.

    The reference models this as {enabled, prefer_larger_groups}
    (mod.rs:71-98, should_switch_tasks mod.rs:257-296):
      NEVER         = enabled=false
      IF_UNASSIGNED = enabled, prefer_larger_groups=false (merge only when
                      no solo in the batch holds a task)
      ALWAYS        = enabled, prefer_larger_groups=true (the default)
    IF_SAME_TASK is this framework's extra conservative variant: merge only
    solos already on the same task (never switches anything)."""

    ALWAYS = "always"
    NEVER = "never"
    IF_UNASSIGNED = "if_unassigned"
    IF_SAME_TASK = "if_same_task"


def _haversine_km_np(lat1, lon1, lat2, lon2) -> np.ndarray:
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2 * 6371.0 * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


class NodeGroupsPlugin:
    def __init__(
        self,
        store: StoreContext,
        configurations: list[NodeGroupConfiguration],
        merge_policy: TaskSwitchingPolicy = TaskSwitchingPolicy.IF_SAME_TASK,
        rng: Optional[random.Random] = None,
    ):
        self.store = store
        self.merge_policy = merge_policy
        self.rng = rng or random.Random()
        self.encoder = FeatureEncoder()
        # optional lifecycle hooks (fed to the webhook plugin)
        self.on_group_created = None
        self.on_group_dissolved = None
        # optional group<->task ranker (wired by the batch matcher so the
        # selection goes through the cost/auction path instead of
        # rng.choice — SURVEY §7 hard part 5). Contract: ranker(group,
        # applicable) returns the chosen Task, or None for "this group
        # deliberately gets nothing this round" (e.g. a replica-bounded
        # topology task's budget is spent on other groups).
        self.task_ranker = None
        # larger min first, then more specific requirements first
        # (mod.rs:150-164)
        self.configurations = sorted(
            configurations,
            key=lambda c: (-c.min_group_size, -c.specificity(), c.name),
        )
        by_name: dict[str, NodeGroupConfiguration] = {}
        for c in self.configurations:
            if c.name in by_name:
                raise ValueError(f"duplicate group configuration name: {c.name}")
            if c.min_group_size <= 0 or c.max_group_size < c.min_group_size:
                raise ValueError(f"invalid size bounds for configuration {c.name}")
            by_name[c.name] = c
        self.config_by_name = by_name

    # ------------- wiring -------------

    def attach_observers(self) -> None:
        self.store.task_store.subscribe_created(self.on_task_created)
        self.store.task_store.subscribe_deleted(self.on_task_deleted)

    # ------------- config enable/disable (mod.rs:1224-1326) -------------

    def on_task_created(self, task: Task) -> None:
        for topo in task.allowed_topologies():
            if topo in self.config_by_name:
                self.store.kv.sadd(ENABLED_CONFIGS, topo)

    def on_task_deleted(self, task: Task) -> None:
        # dissolve this task's groups
        for group in self.get_groups():
            tid = self.store.kv.get(GROUP_TASK_KEY.format(group.id))
            if tid == task.id:
                self.dissolve_group(group.id)
        # disable topologies no remaining task allows
        still_allowed: set[str] = set()
        for t in self.store.task_store.get_all_tasks():
            still_allowed.update(t.allowed_topologies())
        for name in list(self.store.kv.smembers(ENABLED_CONFIGS)):
            if name not in still_allowed:
                self.store.kv.srem(ENABLED_CONFIGS, name)

    def enabled_configurations(self) -> list[NodeGroupConfiguration]:
        enabled = self.store.kv.smembers(ENABLED_CONFIGS)
        return [c for c in self.configurations if c.name in enabled]

    # ------------- group store ops -------------

    def get_groups(self) -> list[NodeGroup]:
        ids = sorted(self.store.kv.smembers(GROUPS_INDEX))
        out = []
        for gid in ids:
            raw = self.store.kv.get(GROUP_KEY.format(gid))
            if raw:
                out.append(NodeGroup.from_dict(json.loads(raw)))
        return out

    def get_group(self, group_id: str) -> Optional[NodeGroup]:
        raw = self.store.kv.get(GROUP_KEY.format(group_id))
        return NodeGroup.from_dict(json.loads(raw)) if raw else None

    def grouped_addresses(self) -> set[str]:
        """All addresses currently in any group (the batch matcher excludes
        them from the individual solve — their work arrives group-wise)."""
        out: set[str] = set()
        for g in self.get_groups():
            out.update(g.nodes)
        return out

    def group_for_node(self, address: str) -> Optional[NodeGroup]:
        gid = self.store.kv.hget(NODE_TO_GROUP, address)
        if gid is None:
            return None
        group = self.get_group(gid)
        if group is None:
            # dissolved-group recovery (scheduler_impl.rs:90-104,
            # mod.rs:1073-1119): stale mapping -> clear it
            self.store.kv.hdel(NODE_TO_GROUP, address)
            return None
        return group

    def _create_group(self, config: NodeGroupConfiguration, members: list[str]) -> NodeGroup:
        group = NodeGroup(configuration_name=config.name, nodes=members)
        with self.store.kv.atomic():  # mirror of the reference's pipeline
            self.store.kv.set(GROUP_KEY.format(group.id), json.dumps(group.to_dict()))
            self.store.kv.sadd(GROUPS_INDEX, group.id)
            for addr in members:
                self.store.kv.hset(NODE_TO_GROUP, addr, group.id)
        if self.on_group_created is not None:
            self.on_group_created(group.to_dict())
        return group

    def dissolve_group(self, group_id: str) -> None:
        with self.store.kv.atomic():
            group = self.get_group(group_id)
            if group is None:
                return
            for addr in group.nodes:
                if self.store.kv.hget(NODE_TO_GROUP, addr) == group_id:
                    self.store.kv.hdel(NODE_TO_GROUP, addr)
            self.store.kv.delete(GROUP_KEY.format(group_id))
            self.store.kv.delete(GROUP_TASK_KEY.format(group_id))
            self.store.kv.srem(GROUPS_INDEX, group_id)
        if self.on_group_dissolved is not None:
            self.on_group_dissolved(group.to_dict())

    # ------------- status-change hook -------------

    def handle_status_change(self, node: OrchestratorNode) -> None:
        """A grouped node leaving Healthy dissolves its group — gang
        semantics: the workload's ring is broken (reference status plugin
        path)."""
        if node.status == NodeStatus.HEALTHY:
            return
        group = self.group_for_node(node.address)
        if group is not None:
            self.dissolve_group(group.id)

    # ------------- management tick (mod.rs:180-203) -------------

    def run_group_management(self) -> dict:
        formed = self.try_form_new_groups()
        merged = self.try_merge_solo_groups()
        return {"formed": formed, "merged": merged}

    def _eligible_nodes(self) -> list[OrchestratorNode]:
        grouped = set(self.store.kv.hgetall(NODE_TO_GROUP))
        return [
            n
            for n in self.store.node_store.get_nodes()
            if n.status == NodeStatus.HEALTHY
            and n.p2p_id
            and n.address not in grouped
        ]

    def try_form_new_groups(self) -> int:
        """Greedy per-config formation with proximity seeding. Eligibility
        for ALL (node, config) pairs is one batched compat_mask solve."""
        configs = self.enabled_configurations()
        nodes = self._eligible_nodes()
        if not configs or not nodes:
            return 0

        ep = self.encoder.encode_providers(
            [n.compute_specs for n in nodes], locations=[n.location for n in nodes]
        )
        er = self.encoder.encode_requirements(
            [c.parsed_requirements() for c in configs]
        )
        mask = np.asarray(compat_mask(ep, er))  # [N, C]
        lat = np.asarray(ep.lat)
        lon = np.asarray(ep.lon)
        has_loc = np.asarray(ep.has_location)

        available = np.ones(len(nodes), bool)
        formed = 0
        for ci, config in enumerate(configs):
            while True:
                idxs = np.nonzero(available & mask[:, ci])[0]
                if len(idxs) < config.min_group_size:
                    break
                # proximity seeding (mod.rs:217-255): seed = first eligible;
                # fill with nearest neighbors (locationless nodes last)
                seed = idxs[0]
                if has_loc[seed]:
                    d = _haversine_km_np(lat[seed], lon[seed], lat[idxs], lon[idxs])
                    d = np.where(has_loc[idxs], d, np.inf)
                else:
                    d = np.zeros(len(idxs))
                order = idxs[np.argsort(d, kind="stable")]
                members = order[: config.max_group_size]
                self._create_group(config, [nodes[i].address for i in members])
                available[members] = False
                formed += 1
        return formed

    def try_merge_solo_groups(self) -> int:
        """Merge single-node groups per configuration (mod.rs:631-860):
        collect compatible solos, build a proximity-ordered merge batch
        (seed = first solo with a located node, nearest first,
        mod.rs:760-850), gate on the task-switching policy
        (should_switch_tasks, mod.rs:257-296), then dissolve + create in
        one atomic pipeline and give the merged group the best applicable
        task (find_best_task_for_group, mod.rs:1122-1188)."""
        if self.merge_policy == TaskSwitchingPolicy.NEVER:
            return 0
        merged = 0
        nodes_by_addr = {
            n.address: n for n in self.store.node_store.get_nodes()
        }
        # ONE store scan: the loop below maintains the solo pool
        # incrementally as batches merge (no rescan per iteration)
        all_solos = [g for g in self.get_groups() if len(g.nodes) == 1]
        task_of = {
            g.id: self.store.kv.get(GROUP_TASK_KEY.format(g.id))
            for g in all_solos
        }
        # existing groups imply their config was enabled at formation time,
        # so merging iterates all configurations (a disabled config simply
        # has no solos left to merge)
        for config in self.configurations:
            pool = [g for g in all_solos if g.configuration_name == config.name]
            while True:
                candidates = pool
                if self.merge_policy == TaskSwitchingPolicy.IF_SAME_TASK:
                    # conservative variant: candidates must already share a
                    # task (or be unassigned) — merge one bucket per pass
                    by_task: dict[Optional[str], list[NodeGroup]] = {}
                    for g in pool:
                        by_task.setdefault(task_of.get(g.id), []).append(g)
                    candidates = next(
                        (
                            b
                            for b in by_task.values()
                            if len(b) >= max(2, config.min_group_size)
                        ),
                        [],
                    )
                elif self.merge_policy == TaskSwitchingPolicy.IF_UNASSIGNED:
                    # batch ONLY unassigned solos: a task-holding solo must
                    # not poison the batch and livelock the rest
                    candidates = [g for g in pool if task_of.get(g.id) is None]
                batch = self._merge_batch(candidates, config, nodes_by_addr)
                if batch is None:
                    break
                batch_tasks = [task_of.get(g.id) for g in batch]
                if not self._should_switch_tasks(batch_tasks):
                    break
                # PRESERVE the proximity order _merge_batch built: ring
                # neighbors (${NEXT_P2P_ADDRESS}) follow list order, so a
                # nearest-first batch yields geographically-local hops
                members = list(
                    dict.fromkeys(a for g in batch for a in g.nodes)
                )
                # a single shared task carries over; otherwise the merged
                # group gets a fresh best-task pick
                distinct = {t for t in batch_tasks if t is not None}
                carried = distinct.pop() if len(distinct) == 1 else None
                with self.store.kv.atomic():
                    for g in batch:
                        self.dissolve_group(g.id)
                    new_group = self._create_group(config, members)
                    task_id = carried
                    if task_id is None:
                        best = self._find_best_task_for_group(new_group)
                        task_id = best.id if best is not None else None
                    if task_id is not None:
                        self.store.kv.set(
                            GROUP_TASK_KEY.format(new_group.id), task_id, nx=True
                        )
                merged += 1
                merged_ids = {g.id for g in batch}
                pool = [g for g in pool if g.id not in merged_ids]
        return merged

    def _merge_batch(
        self,
        solos: list[NodeGroup],
        config: NodeGroupConfiguration,
        nodes_by_addr: dict[str, OrchestratorNode],
    ) -> Optional[list[NodeGroup]]:
        """Proximity-ordered selection of solos to merge (mod.rs:760-850):
        seed deterministically with an endpoint of the CLOSEST located
        pair (the reference seeds with its list's first located group,
        which here would follow random uuid sort order — arbitrary
        geography) and add nearest groups first; fall back to original
        order when nothing has a location. Returns None when no viable
        batch exists."""
        if len(solos) < 2:
            return None

        def loc(g: NodeGroup):
            node = nodes_by_addr.get(g.nodes[0])
            return node.location if node is not None else None

        batch: list[NodeGroup] = []
        located = [g for g in solos if loc(g) is not None]
        seed = None
        if len(located) >= 2:
            lat = np.radians([loc(g).latitude for g in located])
            lon = np.radians([loc(g).longitude for g in located])
            d = _haversine_km_np(lat[:, None], lon[:, None], lat[None, :], lon[None, :])
            np.fill_diagonal(d, np.inf)
            seed = located[int(np.argmin(d.min(axis=1)))]
        elif located:
            seed = located[0]
        if seed is not None:
            sloc = loc(seed)
            batch.append(seed)
            remaining = [
                (s, g)
                for g in solos
                if g.id != seed.id
                for lg in [loc(g)]
                if lg is not None
                for s in [
                    float(
                        _haversine_km_np(
                            np.radians(sloc.latitude),
                            np.radians(sloc.longitude),
                            np.radians(lg.latitude),
                            np.radians(lg.longitude),
                        )
                    )
                ]
            ]
            remaining.sort(key=lambda sg: sg[0])
            for _d, g in remaining:
                if len(batch) >= config.max_group_size:
                    break
                batch.append(g)
        if len(batch) < max(2, config.min_group_size):
            # fallback: original order, location-blind (mod.rs:823-849)
            batch = solos[: config.max_group_size]
        if len(batch) < max(2, config.min_group_size):
            return None
        return batch

    def _should_switch_tasks(self, batch_tasks: list[Optional[str]]) -> bool:
        """should_switch_tasks (mod.rs:257-296) over the policy enum."""
        if self.merge_policy == TaskSwitchingPolicy.ALWAYS:
            return True
        if self.merge_policy == TaskSwitchingPolicy.IF_UNASSIGNED:
            # prefer_larger_groups=false: any held task blocks the merge
            return all(t is None for t in batch_tasks)
        if self.merge_policy == TaskSwitchingPolicy.IF_SAME_TASK:
            return len({t for t in batch_tasks if t is not None}) <= 1
        return False

    def _find_best_task_for_group(self, group: NodeGroup) -> Optional[Task]:
        """find_best_task_for_group (mod.rs:1122-1188): tasks with NO
        topology restriction are compatible with any group; restricted
        tasks must list this group's configuration. Random pick."""
        applicable = [
            t
            for t in self.store.task_store.get_all_tasks()
            if not t.allowed_topologies()
            or group.configuration_name in t.allowed_topologies()
        ]
        if not applicable:
            return None
        if self.task_ranker is not None:
            return self.task_ranker(group, applicable)
        return self.rng.choice(applicable)

    # ------------- scheduler-side filter (scheduler_impl.rs) -------------

    def filter_tasks(self, tasks: list[Task], node: OrchestratorNode) -> list[Task]:
        group = self.group_for_node(node.address)
        if group is None:
            # topology-scheduled pools give ungrouped nodes nothing
            return []

        task = self._task_for_group(group, tasks)
        if task is None:
            return []
        return [self._expand_group_vars(task, group, node.address)]

    def _task_for_group(self, group: NodeGroup, tasks: list[Task]) -> Optional[Task]:
        key = GROUP_TASK_KEY.format(group.id)
        tid = self.store.kv.get(key)
        if tid is not None:
            task = next((t for t in tasks if t.id == tid), None)
            if task is not None:
                return task
            # stale-task cleanup is COMPARE-and-delete (the reference's Lua
            # script, mod.rs:447-467): another scheduler may have just
            # SET-NX'd a fresh task under this key — deleting blindly would
            # throw its assignment away
            with self.store.kv.atomic():
                if self.store.kv.get(key) == tid:
                    self.store.kv.delete(key)
        applicable = [
            t for t in tasks if group.configuration_name in t.allowed_topologies()
        ]
        if self.task_ranker is not None:
            # Composed mode: the matcher's group solve decides, and its
            # universe includes unrestricted UNBOUNDED tasks (the
            # reference's own recovery path hands those to groups,
            # mod.rs:1122-1188 — the heartbeat path merely never offered
            # them). Replica-bounded unrestricted tasks stay individual-
            # only: their budget is accounted in the individual solve.
            from protocol_tpu.sched.tpu_backend import task_replicas

            for t in tasks:
                if t.allowed_topologies():
                    continue
                try:
                    if task_replicas(t) is None:
                        applicable.append(t)
                except ValueError:
                    continue
            if not applicable:
                return None
            choice = self.task_ranker(group, applicable)
            if choice is None:
                return None
        elif not applicable:
            return None
        else:
            choice = self.rng.choice(applicable)  # mod.rs:1176-1188
        # SET NX: first scheduler wins the race (mod.rs:471-476)
        self.store.kv.set(key, choice.id, nx=True)
        tid = self.store.kv.get(key)
        return next((t for t in tasks if t.id == tid), None)

    def _expand_group_vars(
        self, task: Task, group: NodeGroup, node_address: str
    ) -> Task:
        """${GROUP_*} / ring-neighbor / upload-counter expansion
        (scheduler_impl.rs:112-205)."""
        t = copy.deepcopy(task)
        index = group.nodes.index(node_address)
        size = len(group.nodes)
        next_addr = group.nodes[(index + 1) % size]
        next_node = self.store.node_store.get_node(next_addr)
        next_p2p = ""
        if next_node and next_node.p2p_addresses:
            next_p2p = next_node.p2p_addresses[0]
        elif next_node and next_node.p2p_id:
            next_p2p = next_node.p2p_id

        total_uploads = 0
        last_idx = 0
        if t.storage_config and t.storage_config.file_name_template:
            counter_key = UPLOAD_COUNTER_KEY.format(
                node_address, group.id, t.storage_config.file_name_template
            )
            raw = self.store.kv.get(counter_key)
            total_uploads = int(raw) if raw else 0
            last_idx = max(0, total_uploads - 1)

        mapping = {
            "${GROUP_ID}": group.id,
            "${GROUP_INDEX}": str(index),
            "${GROUP_SIZE}": str(size),
            "${NEXT_P2P_ADDRESS}": next_p2p,
            "${TOTAL_UPLOAD_COUNT}": str(total_uploads),
            "${LAST_FILE_IDX}": str(last_idx),
        }

        def sub(s: str) -> str:
            for k, v in mapping.items():
                s = s.replace(k, v)
            return s

        if t.env_vars:
            t.env_vars = {k: sub(v) for k, v in t.env_vars.items()}
        if t.cmd:
            t.cmd = [sub(c) for c in t.cmd]
        if t.entrypoint:
            t.entrypoint = [sub(c) for c in t.entrypoint]
        if t.volume_mounts:
            t.volume_mounts = [
                type(vm)(host_path=sub(vm.host_path), container_path=sub(vm.container_path))
                for vm in t.volume_mounts
            ]
        return t
