"""Incremental candidate cache: the delta-aware half of the warm solve.

SURVEY §7 hard part 4: the reference re-walks every task per heartbeat
(crates/orchestrator/src/scheduler/mod.rs:26-74); a naive batched re-solve
every population change re-pays the dominant stage — candidate generation,
an O(P*T) streamed pass — even when one node joined. This cache makes the
candidate structure itself persistent:

  - **Row-stable provider registry.** Every address gets a row that never
    moves until compaction; departed providers are masked invalid, changed
    specs retire the row and allocate a fresh one. Columnar feature arrays
    (the EncodedProviders fields) grow append-only, so per-solve encoding
    cost is O(churn), not O(P).
  - **Per-task candidate entries.** Each bounded task caches its slots'
    top-K candidate rows plus the *static* part of their costs (proximity +
    tie-jitter — everything except per-provider price/load and per-task
    priority, which are re-applied at assembly). New tasks compute fresh
    columns; new providers merge into cached lists via a small
    [delta-P x S] pass — never the full [P x S] tensor.
  - **Auction dual state.** Prices live per-row and survive churn, so the
    frontier auction re-bids only the delta (ops/sparse.py
    assign_auction_sparse_warm).

Cost decomposition invariant (ops/cost.py): cost[p, t] =
  base[p] (price/load terms) + static[p, t] (proximity + jitter)
  - w_priority * prio[t], with INFEASIBLE for incompatible pairs.
Per-provider and per-task terms shift whole rows/columns, so the cached
*selection* stays valid under price/load/priority drift; values are exact
because base and priority are re-applied from current state at assembly.
Selection staleness from base drift is bounded by periodic rebuilds.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax.numpy as jnp

from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.ops.encoding import (
    EncodedProviders,
    EncodedRequirements,
    FeatureEncoder,
)
from protocol_tpu.ops.sparse import candidates_topk, candidates_topk_reverse

_P_FIELDS = (
    "gpu_count", "gpu_mem_mb", "gpu_model_id", "has_gpu", "has_cpu",
    "cpu_cores", "ram_mb", "storage_gb", "lat", "lon", "has_location",
    "price", "load", "valid",
)
# integer columns whose "absent" sentinel is -1 (a 0 fill would read as a
# real reported value to compat_mask, e.g. "0 cores")
_P_INT_FIELDS = frozenset(
    ("gpu_count", "gpu_mem_mb", "gpu_model_id", "cpu_cores", "ram_mb",
     "storage_gb")
)


def _pow2(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


@dataclass
class ProviderItem:
    addr: str
    specs: object  # Optional[ComputeSpecs]
    location: object  # Optional[NodeLocation]
    price: float = 0.0
    load: float = 0.0

    def fingerprint(self) -> tuple:
        """Cheap structural identity for change detection — hand-rolled
        field tuple, NOT to_dict/json (this runs once per provider per
        solve; asdict costs ~30us each and dominated the warm path)."""
        s = self.specs
        g = s.gpu if s is not None else None
        c = s.cpu if s is not None else None
        loc = self.location
        return (
            (g.count, g.model, g.memory_mb) if g is not None else None,
            (c.cores,) if c is not None else None,
            (s.ram_mb, s.storage_gb) if s is not None else None,
            (loc.latitude, loc.longitude) if loc is not None else None,
        )


@dataclass
class TaskItem:
    task_id: str
    requirement: object  # ComputeRequirements
    take: int  # replica slots this solve
    prio: float = 0.0

    def req_key(self) -> tuple:
        """Cheap structural identity over the ENCODED requirement fields —
        hand-rolled tuple, not to_dict/json, for the same reason as
        ProviderItem.fingerprint: this runs once per task per solve."""
        r = self.requirement
        return (
            (r.cpu.cores,) if r.cpu is not None else None,
            r.ram_mb,
            r.storage_gb,
            tuple(
                (g.count, g.model, g.memory_mb, g.memory_mb_min,
                 g.memory_mb_max, g.total_memory_min, g.total_memory_max)
                for g in r.gpu
            ),
        )


@dataclass
class _TaskEntry:
    req_key: str
    take: int
    vocab_version: int
    cand_p: np.ndarray  # [take, k] global rows, -1 pad
    cand_static: np.ndarray  # [take, k] f32 cost minus base minus priority
    er_row: dict  # single-row numpy EncodedRequirements fields


@dataclass
class PreparedSolve:
    ep: EncodedProviders  # padded to p_bucket
    cand_p: np.ndarray  # [S_pad, k]
    cand_c: np.ndarray  # [S_pad, k] current full costs
    price0: np.ndarray  # [p_bucket] f32
    row_of_addr: dict
    addr_of_row: list
    num_rows: int
    p_bucket: int
    num_slots: int
    rebuilt: bool
    delta_tasks: int
    delta_rows: int
    # valid provider rows that appeared in NO task's cached top-k list and
    # were given reverse edges by the coverage repair (0 = full coverage)
    uncovered_rows: int = 0
    # fraction of valid rows whose base (price/load) drifted beyond the
    # selection tolerance since their candidates were chosen — the adaptive
    # re-ground trigger (measured BEFORE any rebuild this prepare)
    stale_frac: float = 0.0
    # [S_pad] bool: slots whose ASSEMBLED candidate lists differ from the
    # previous prepare (fresh tasks, provider churn merges, departures,
    # coverage-repair shifts). The warm kernel's contract says rows whose
    # candidates changed must have their carried retirement flags cleared
    # by the caller — this is that signal. None on the first prepare /
    # after a rebuild (treat every slot as dirty).
    dirty_slots: Optional[np.ndarray] = None


class CandidateCache:
    def __init__(
        self,
        encoder: FeatureEncoder,
        weights: CostWeights,
        k: int = 64,
        max_invalid_frac: float = 0.25,
        reverse_r: int = 8,
        extra: int = 16,
        stale_rel_tol: float = 0.25,
        stale_abs_tol: float = 0.05,
        max_stale_frac: float | None = 0.10,
    ):
        self.encoder = encoder
        # candidate SELECTION is priority-free: the priority term shifts a
        # task's whole row uniformly and can't change its provider ranking
        self.weights = weights
        self._sel_weights = dataclasses.replace(weights, priority=0.0)
        self.k = k
        self.max_invalid_frac = max_invalid_frac
        # Adaptive re-ground (replaces schedule-only cold solves): cached
        # SELECTION was made under the base (price/load) vector at
        # registration time; base drift re-ranks providers and silently
        # degrades the cached top-k. A row is "stale" when its
        # MEAN-CENTERED drift (uniform shifts preserve ranking) exceeds
        # ``stale_rel_tol`` x the fleet's current base spread; a prepare
        # that finds more than ``max_stale_frac`` stale rows rebuilds
        # in place (None disables the trigger). ``stale_abs_tol`` is the
        # absolute floor in cost units: on a homogeneous fleet the base
        # spread collapses to ~0 and, without the floor, load-average
        # jitter (~0.01-0.02 in cost units) reads as "re-ranked" and
        # rebuilds every solve (measured in the full-stack soak — warm
        # never engaged). Re-ranking among near-ties is what the tie
        # jitter randomizes anyway; only drift big enough to matter
        # against real price/load differentiation should trigger.
        self.stale_rel_tol = stale_rel_tol
        self.stale_abs_tol = stale_abs_tol
        self.max_stale_frac = max_stale_frac
        # coverage repair: rows absent from EVERY cached list get up to
        # ``reverse_r`` reverse (provider->slot) edges, scattered into
        # ``extra`` fixed extra candidate columns per slot (fixed so the
        # auction executable shape stays bucket-stable across solves)
        self.reverse_r = reverse_r
        self.extra = extra
        self._clear()

    # ---------------- provider registry ----------------

    def _clear(self) -> None:
        self.rows = 0
        self.row_of_addr: dict[str, int] = {}
        self.addr_of_row: list[Optional[str]] = []
        self.fp_of_addr: dict[str, str] = {}
        self.cols: dict[str, np.ndarray] = {}
        self.prices = np.zeros(0, np.float32)
        # base (price/load cost terms) as of each row's candidate
        # SELECTION — the drift reference for the adaptive re-ground
        self.sel_base = np.zeros(0, np.float32)
        self.entries: dict[str, _TaskEntry] = {}
        # persistent jitter cursor: delta batches must not restart the
        # tie-jitter's task index at 0, or tasks registered one per solve
        # on a homogeneous fleet would all cache the SAME k providers
        # (capping the matching at k) — see candidates_topk(task_offset=...)
        self._jitter_cursor = 0
        # previous prepare's assembled lists: the reference for dirty_slots
        self._prev_cand_p: Optional[np.ndarray] = None
        self._prev_cand_c: Optional[np.ndarray] = None

    def invalidate(self) -> None:
        """Force a full rebuild on the next prepare (the periodic cold
        solve that re-grounds prices and candidate selection)."""
        self._clear()

    def _grow(self, need: int) -> None:
        cap = self.prices.shape[0]
        if need <= cap:
            return
        new_cap = _pow2(need)
        self.prices = np.concatenate(
            [self.prices, np.zeros(new_cap - cap, np.float32)]
        )
        self.sel_base = np.concatenate(
            [self.sel_base, np.zeros(new_cap - cap, np.float32)]
        )
        for name, arr in self.cols.items():
            pad = np.zeros((new_cap - cap,) + arr.shape[1:], arr.dtype)
            if name in _P_INT_FIELDS:
                pad.fill(-1)
            self.cols[name] = np.concatenate([arr, pad])

    def _register_batch(self, items: list[ProviderItem]) -> np.ndarray:
        """Encode a batch of new/changed providers and append rows.
        Returns the new global row indices."""
        n = len(items)
        enc = self.encoder.encode_providers(
            [it.specs for it in items],
            locations=[it.location for it in items],
            prices=[it.price for it in items],
            loads=[it.load for it in items],
        )
        lo = self.rows
        self._grow(lo + n)
        if not self.cols:
            # first registration: materialize columns at current capacity
            cap = self.prices.shape[0]
            for name in _P_FIELDS:
                a = np.asarray(getattr(enc, name))
                col = np.zeros((cap,) + a.shape[1:], a.dtype)
                if name in _P_INT_FIELDS:
                    col.fill(-1)
                self.cols[name] = col
        for name in _P_FIELDS:
            self.cols[name][lo:lo + n] = np.asarray(getattr(enc, name))
        w = self.weights
        self.sel_base[lo:lo + n] = [
            w.price * it.price + w.load * it.load for it in items
        ]
        rows = np.arange(lo, lo + n, dtype=np.int32)
        for i, it in enumerate(items):
            old = self.row_of_addr.get(it.addr)
            if old is not None:
                self.cols["valid"][old] = False
            self.row_of_addr[it.addr] = lo + i
            self.fp_of_addr[it.addr] = it.fingerprint()
        self.addr_of_row.extend(it.addr for it in items)
        self.rows = lo + n
        return rows

    def _pad_k(self, cp: np.ndarray, cs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """candidates_topk clamps k to the provider count: normalize cached
        entries to self.k columns so assembly/merge shapes always line up."""
        have = cp.shape[1]
        if have >= self.k:
            return cp[:, : self.k], cs[:, : self.k]
        padp = np.full((cp.shape[0], self.k - have), -1, np.int32)
        pads = np.zeros((cp.shape[0], self.k - have), np.float32)
        return np.concatenate([cp, padp], axis=1), np.concatenate([cs, pads], axis=1)

    def _base_now(self) -> np.ndarray:
        w = self.weights
        return (
            w.price * self.cols["price"][: self.rows]
            + w.load * self.cols["load"][: self.rows]
        ).astype(np.float32)

    def _assemble_ep(self, p_bucket: int) -> EncodedProviders:
        kw = {}
        for name in _P_FIELDS:
            col = self.cols[name][: self.rows]
            pad = np.zeros((p_bucket - self.rows,) + col.shape[1:], col.dtype)
            if name in _P_INT_FIELDS:
                pad.fill(-1)
            kw[name] = jnp.asarray(np.concatenate([col, pad]))
        return EncodedProviders(**kw)

    # ---------------- requirements tiling ----------------

    def _encode_req_row(self, item: TaskItem) -> dict:
        enc = self.encoder.encode_requirements([item.requirement])
        return {
            f.name: np.asarray(getattr(enc, f.name))
            for f in dataclasses.fields(enc)
        }

    @staticmethod
    def _tile_er(rows: list[tuple[dict, int, float]], pad_to: int) -> EncodedRequirements:
        """Assemble an EncodedRequirements by repeating cached single-row
        encodings ``take`` times each (slots of a task share the
        requirement; priority applied per slot)."""
        fields = {}
        names = list(rows[0][0].keys())
        for name in names:
            parts = [np.repeat(r[name], take, axis=0) for r, take, _ in rows]
            total = sum(p.shape[0] for p in parts)
            arr = np.concatenate(parts)
            if pad_to > total:
                pad = np.zeros((pad_to - total,) + arr.shape[1:], arr.dtype)
                if name in ("cpu_cores", "ram_mb", "storage_gb", "gpu_count",
                            "gpu_mem_min", "gpu_mem_max",
                            "gpu_total_mem_min", "gpu_total_mem_max"):
                    pad.fill(-1)
                arr = np.concatenate([arr, pad])
            fields[name] = arr
        prio = np.zeros(pad_to, np.float32)
        valid = np.zeros(pad_to, bool)
        off = 0
        for r, take, p in rows:
            prio[off:off + take] = p
            valid[off:off + take] = True
            off += take
        fields["priority"] = prio
        fields["valid"] = valid
        return EncodedRequirements(
            **{k: jnp.asarray(v) for k, v in fields.items()}
        )

    # ---------------- the solve preparation ----------------

    def prepare(self, providers: list[ProviderItem], tasks: list[TaskItem]) -> PreparedSolve:
        """Sync registry + entries with the current population and return
        the assembled solve inputs. O(churn * S) work, not O(P * S), when
        the population is mostly unchanged."""
        # ---- departures first: mask rows whose addr is gone
        current_addrs = {it.addr for it in providers}
        for addr, row in list(self.row_of_addr.items()):
            if addr not in current_addrs:
                self.cols["valid"][row] = False
                del self.row_of_addr[addr]
                self.fp_of_addr.pop(addr, None)
        # ---- compaction trigger: too many dead rows -> full rebuild
        if self.rows:
            live = int(self.cols["valid"][: self.rows].sum())
            if (self.rows - live) / self.rows > self.max_invalid_frac:
                self._clear()
        rebuilt = self.rows == 0

        # ---- provider sync
        delta_items: list[ProviderItem] = []
        for it in providers:
            row = self.row_of_addr.get(it.addr)
            if row is None or self.fp_of_addr.get(it.addr) != it.fingerprint():
                delta_items.append(it)
            else:
                # cheap per-solve drift: price/load update in place
                self.cols["price"][row] = it.price
                self.cols["load"][row] = it.load
        new_rows = (
            self._register_batch(delta_items)
            if delta_items
            else np.zeros(0, np.int32)
        )

        # ---- adaptive re-ground: staleness bounded by MEASUREMENT, not
        # schedule. If base drift has re-ranked too much of the fleet
        # since selection, rebuild now (one recursion; the fresh cache
        # reports rebuilt=True and skips this check).
        stale_frac = self._stale_fraction()
        if (
            not rebuilt
            and self.max_stale_frac is not None
            and stale_frac > self.max_stale_frac
        ):
            self._clear()
            prep = self.prepare(providers, tasks)
            return dataclasses.replace(prep, stale_frac=stale_frac)

        p_bucket = _pow2(self.rows)
        ep = self._assemble_ep(p_bucket)
        base = self._base_now()

        # ---- task sync
        current_ids = {t.task_id for t in tasks}
        for tid in [t for t in self.entries if t not in current_ids]:
            del self.entries[tid]
        vocab = self.encoder.vocab_version
        delta_tasks = [
            t for t in tasks
            if (e := self.entries.get(t.task_id)) is None
            or e.take != t.take
            or e.req_key != t.req_key()
            or e.vocab_version != vocab
        ]
        fresh_ids = {t.task_id for t in delta_tasks}

        if delta_tasks:
            rows_meta = [
                (self._encode_req_row(t), t.take, 0.0) for t in delta_tasks
            ]
            sd = sum(t.take for t in delta_tasks)
            sd_pad = _pow2(sd)
            er_d = self._tile_er(rows_meta, sd_pad)
            tile = min(1024, sd_pad)
            cp, cc = candidates_topk(
                ep, er_d, self._sel_weights, k=self.k, tile=tile,
                task_offset=self._jitter_cursor,
            )
            self._jitter_cursor += sd_pad
            cp = np.asarray(cp)[:sd]
            cc = np.asarray(cc)[:sd]
            static = np.where(
                cp >= 0, cc - base[np.maximum(cp, 0)], 0.0
            ).astype(np.float32)
            off = 0
            for (er_row, take, _), t in zip(rows_meta, delta_tasks):
                e_cp, e_cs = self._pad_k(
                    cp[off:off + take], static[off:off + take]
                )
                self.entries[t.task_id] = _TaskEntry(
                    req_key=t.req_key(),
                    take=take,
                    vocab_version=vocab,
                    cand_p=e_cp.copy(),
                    cand_static=e_cs.copy(),
                    er_row=er_row,
                )
                off += take

        # ---- merge new providers into UNCHANGED cached tasks
        stale_tasks = [t for t in tasks if t.task_id not in fresh_ids]
        if len(new_rows) and stale_tasks:
            self._merge_new_rows(ep, new_rows, stale_tasks, base)

        # ---- assembly
        S = sum(t.take for t in tasks)
        s_pad = _pow2(S)
        cand_p = np.full((s_pad, self.k), -1, np.int32)
        cand_c = np.zeros((s_pad, self.k), np.float32)
        slot_prio = np.zeros(s_pad, np.float32)
        valid_row = self.cols["valid"][: self.rows]
        wprio = self.weights.priority
        off = 0
        for t in tasks:
            e = self.entries[t.task_id]
            cp = e.cand_p
            # departed/retired rows fall out of the matching here
            cp = np.where((cp >= 0) & valid_row[np.maximum(cp, 0)], cp, -1)
            cand_p[off:off + t.take] = cp
            cand_c[off:off + t.take] = np.where(
                cp >= 0,
                e.cand_static + base[np.maximum(cp, 0)] - wprio * t.prio,
                0.0,
            )
            slot_prio[off:off + t.take] = t.prio
            off += t.take

        # ---- coverage repair: per-task top-k windows pile onto the same
        # cheap providers (price-dominated costs), so at scale a fraction
        # of valid rows appears in NO list — unreachable by the auction no
        # matter how prices move, capping the warm matching exactly like
        # the forward-only cold path (ops/sparse.candidates_topk_reverse
        # docstring has the measurement). Give those rows reverse edges.
        cand_p, cand_c, uncovered = self._repair_coverage(
            cand_p, cand_c, tasks, valid_row, slot_prio, s_pad, wprio
        )

        # dirty-slot tracking for the warm retirement carry: compare the
        # fully-assembled lists (forward + repair extras) against the
        # previous prepare — content comparison catches every source of
        # change at once (fresh tasks, merges, departures, repair shifts)
        if (
            self._prev_cand_p is not None
            and self._prev_cand_p.shape == cand_p.shape
        ):
            dirty_slots = (cand_p != self._prev_cand_p).any(axis=1)
            # cost-only drift (price/load updated in place) changes cand_c
            # without touching the provider ids. A retired task can only
            # become viable again when something in its row got CHEAPER,
            # so material decreases dirty the row too; increases cannot
            # un-retire, and sub-tolerance load jitter must not break the
            # carry (stale_abs_tol is the same floor the adaptive
            # re-ground uses for "drift big enough to matter").
            dirty_slots |= (
                (self._prev_cand_c - cand_c) > self.stale_abs_tol
            ).any(axis=1)
        else:
            dirty_slots = None  # first prepare / slot relayout: all dirty
        self._prev_cand_p = cand_p.copy()
        self._prev_cand_c = cand_c.copy()

        return PreparedSolve(
            ep=ep,
            cand_p=cand_p,
            cand_c=cand_c,
            price0=np.concatenate(
                [self.prices[: self.rows],
                 np.zeros(p_bucket - self.rows, np.float32)]
            ),
            row_of_addr=self.row_of_addr,
            addr_of_row=self.addr_of_row,
            num_rows=self.rows,
            p_bucket=p_bucket,
            num_slots=S,
            rebuilt=rebuilt,
            delta_tasks=len(delta_tasks),
            delta_rows=int(len(new_rows)),
            uncovered_rows=uncovered,
            stale_frac=stale_frac,
            dirty_slots=dirty_slots,
        )

    def _stale_fraction(self) -> float:
        """Fraction of valid rows whose base drifted beyond the selection
        tolerance. Drift is mean-centered (a uniform fleet-wide shift —
        inflation — moves every row's cost equally and cannot re-rank) and
        scaled by the current base SPREAD (the scale provider rankings
        live on)."""
        if self.rows == 0:
            return 0.0
        valid = self.cols["valid"][: self.rows]
        if not valid.any():
            return 0.0
        now = self._base_now()[valid]
        sel = self.sel_base[: self.rows][valid]
        d = now - sel
        d = d - d.mean()
        tol = self.stale_rel_tol * float(np.std(now)) + self.stale_abs_tol
        return float((np.abs(d) > tol).mean())

    def _sub_ep(self, rows: np.ndarray) -> EncodedProviders:
        """Assemble an EncodedProviders view of a row subset (padded to a
        pow2 bucket) — shared by the new-row merge and coverage repair."""
        d_pad = _pow2(len(rows))
        sub = {}
        for name in _P_FIELDS:
            col = self.cols[name][rows]
            pad = np.zeros((d_pad - len(rows),) + col.shape[1:], col.dtype)
            if name in _P_INT_FIELDS:
                pad.fill(-1)
            sub[name] = jnp.asarray(np.concatenate([col, pad]))
        return EncodedProviders(**sub)

    def _repair_coverage(
        self,
        cand_p: np.ndarray,
        cand_c: np.ndarray,
        tasks: list[TaskItem],
        valid_row: np.ndarray,
        slot_prio: np.ndarray,
        s_pad: int,
        wprio: float,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Append ``self.extra`` candidate columns holding reverse edges
        for valid rows that appear in no list. One [U x S] streamed pass
        over only the uncovered rows — O(uncovered), not O(P) — then a
        host scatter capped at ``extra`` per slot (cheapest win). Dedup
        against forward lists is unnecessary: uncovered rows by definition
        appear in none of them.

        The pass re-runs each prepare (uncovered rows stay uncovered in
        the forward lists). Like the forward selection, reverse selection
        is price-drift-stable (base shifts a provider's whole row
        uniformly), so these edges could be cached per-provider if the
        [U x S] pass ever shows up in solve profiles."""
        extra_p = np.full((s_pad, self.extra), -1, np.int32)
        extra_c = np.zeros((s_pad, self.extra), np.float32)
        covered = np.zeros(self.rows, bool)
        flat = cand_p[cand_p >= 0]
        if flat.size:
            covered[flat] = True
        uncovered = np.flatnonzero(valid_row & ~covered)
        if uncovered.size and tasks:
            sub_ep = self._sub_ep(uncovered)
            rows_meta = [
                (self.entries[t.task_id].er_row, t.take, 0.0) for t in tasks
            ]
            er = self._tile_er(rows_meta, s_pad)
            r = min(self.reverse_r, s_pad)
            _, _, rev_t, rev_c = candidates_topk_reverse(
                sub_ep, er, self._sel_weights, k=1,
                tile=min(1024, s_pad), reverse_r=r,
                task_offset=self._jitter_cursor,
            )
            self._jitter_cursor += s_pad
            U = uncovered.size
            rt = np.asarray(rev_t)[:U]
            rc = np.asarray(rev_c)[:U]
            ok = rt >= 0
            slot = rt[ok]
            cost = rc[ok]
            prov = np.broadcast_to(uncovered[:, None].astype(np.int32), rt.shape)[ok]
            order = np.lexsort((cost, slot))
            slot, cost, prov = slot[order], cost[order], prov[order]
            idxs = np.arange(slot.size)
            first = np.r_[True, slot[1:] != slot[:-1]] if slot.size else np.zeros(0, bool)
            start = np.maximum.accumulate(np.where(first, idxs, 0))
            rank = idxs - start
            keep = rank < self.extra
            extra_p[slot[keep], rank[keep]] = prov[keep]
            extra_c[slot[keep], rank[keep]] = (
                cost[keep] - wprio * slot_prio[slot[keep]]
            )
        return (
            np.concatenate([cand_p, extra_p], axis=1),
            np.concatenate([cand_c, extra_c], axis=1),
            int(uncovered.size),
        )

    def _merge_new_rows(
        self,
        ep: EncodedProviders,
        new_rows: np.ndarray,
        tasks: list[TaskItem],
        base: np.ndarray,
    ) -> None:
        """Fold newly-registered provider rows into cached candidate lists:
        one [delta-P x S] candidate pass + a host-side per-slot merge."""
        d_pad = _pow2(len(new_rows))
        ep_d = self._sub_ep(new_rows)

        rows_meta = [
            (self.entries[t.task_id].er_row, t.take, 0.0) for t in tasks
        ]
        S = sum(t.take for t in tasks)
        s_pad = _pow2(S)
        er = self._tile_er(rows_meta, s_pad)
        tile = min(1024, s_pad)
        kd = min(self.k, d_pad)
        cp_d, cc_d = candidates_topk(
            ep_d, er, self._sel_weights, k=kd, tile=tile,
            task_offset=self._jitter_cursor,
        )
        self._jitter_cursor += s_pad
        cp_d = np.asarray(cp_d)[:S]
        cc_d = np.asarray(cc_d)[:S]
        valid_row = self.cols["valid"][: self.rows]
        cp_d = np.where(cp_d >= 0, new_rows[np.maximum(cp_d, 0)], -1)
        static_d = np.where(
            cp_d >= 0, cc_d - base[np.maximum(cp_d, 0)], 0.0
        ).astype(np.float32)

        off = 0
        for t in tasks:
            e = self.entries[t.task_id]
            take = e.take
            allp = np.concatenate([e.cand_p, cp_d[off:off + take]], axis=1)
            alls = np.concatenate(
                [e.cand_static, static_d[off:off + take]], axis=1
            )
            # rank by CURRENT total cost; -1 entries AND dead rows sort
            # last (a departed provider's stale entry must not hold a top-k
            # slot against a live newcomer — the list would silently erode
            # to fewer than k live candidates until a full rebuild)
            live = (allp >= 0) & valid_row[np.maximum(allp, 0)]
            key = np.where(live, alls + base[np.maximum(allp, 0)], np.inf)
            idx = np.argsort(key, axis=1, kind="stable")[:, : self.k]
            e.cand_p, e.cand_static = self._pad_k(
                np.take_along_axis(allp, idx, axis=1),
                np.take_along_axis(alls, idx, axis=1),
            )
            off += take

    def store_prices(self, price: np.ndarray) -> None:
        """Persist the auction's dual state (indexed by row)."""
        self.prices[: self.rows] = np.asarray(
            price[: self.rows], np.float32
        )


class CandidateMemo:
    """Content-hash memo for the UNCACHED candidate paths (VERDICT r4
    item 3): the gRPC backend and the wire-path matcher regenerate full
    bidirectional candidates every solve even when the fleet is
    byte-identical to the previous heartbeat — an O(P*T) streamed pass
    re-paid for a zero-delta input. This memo keys the generated
    [T, K_eff] structure on a hash of the ENCODED inputs plus every
    generation parameter: a changed price, spec, priority, or padding row
    changes the bytes and misses (exactness preserved); the steady-state
    heartbeat loop hits. Hashing is O(P + T) bytes (~ms at 65k) vs
    generation's O(P*T) (~minutes at 65k CPU).

    Unlike :class:`CandidateCache` (row-stable registry, O(churn)
    incremental merge), this is a pure memo — it cannot exploit partial
    overlap, only exact repeats — which is precisely the stateless wire
    contract where the richer cache cannot live."""

    def __init__(self, capacity: int = 4):
        self.capacity = capacity
        self._slots: dict[tuple, tuple] = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _fingerprint(enc) -> bytes:
        import hashlib

        h = hashlib.sha1()
        for f in dataclasses.fields(enc):
            h.update(np.asarray(getattr(enc, f.name)).tobytes())
        return h.digest()

    def get(self, ep, er, weights, *, k, tile, reverse_r, extra,
            approx_recall=None, gen=None):
        """``gen`` overrides the generator (e.g. the task-sharded mesh
        twin) — it shares the memo key because the sharded generator is
        bit-identical to the single-device one (tested parity), so hits
        are interchangeable across paths."""
        from protocol_tpu.ops.sparse import candidates_topk_bidir

        key = (
            self._fingerprint(ep), self._fingerprint(er),
            dataclasses.astuple(weights), k, tile, reverse_r, extra,
            approx_recall,
        )
        hit = self._slots.pop(key, None)
        if hit is not None:
            self.hits += 1
            self._slots[key] = hit  # re-insert: LRU order
            return hit
        self.misses += 1
        gen_fn = gen or candidates_topk_bidir
        out = gen_fn(
            ep, er, weights, k=k, tile=tile, reverse_r=reverse_r,
            extra=extra, approx_recall=approx_recall,
        )
        self._slots[key] = out
        while len(self._slots) > self.capacity:
            self._slots.pop(next(iter(self._slots)))
        return out
