"""Webhook plugin: async event delivery to external endpoints.

Reference: crates/orchestrator/src/plugins/webhook/mod.rs — a bounded-
channel webhook sender fed by node status changes and group lifecycle
events, with per-pool configs from the WEBHOOK_CONFIGS env JSON.

Here: an asyncio bounded queue + drainer posting JSON events; drop-oldest
on overflow (delivery is best-effort in the reference too). Event shapes:
  {"type": "node_status_changed", "address", "old_status", "new_status"}
  {"type": "group_created" | "group_destroyed", "group": {...}}
  {"type": "metrics", "payload": {...}}   (metrics/webhook_sender.rs)
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class WebhookConfig:
    url: str
    # reference configs carry optional event filters per pool
    event_types: Optional[list[str]] = None

    @classmethod
    def from_json_env(cls, raw: str) -> list["WebhookConfig"]:
        """Parse the WEBHOOK_CONFIGS-style env JSON: a list of
        {"url": ..., "event_types": [...]} objects."""
        out = []
        for item in json.loads(raw):
            out.append(
                cls(url=item["url"], event_types=item.get("event_types"))
            )
        return out


class WebhookPlugin:
    def __init__(
        self,
        configs: list[WebhookConfig],
        http=None,  # aiohttp.ClientSession-compatible
        queue_size: int = 1000,
    ):
        self.configs = configs
        self.http = http
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=queue_size)
        self.dropped = 0
        self.delivered = 0
        self._drainer: Optional[asyncio.Task] = None

    # ----- event intake (sync-callable from store/status code) -----

    def emit(self, event_type: str, **payload) -> None:
        event = {"type": event_type, "at": time.time(), **payload}
        try:
            self.queue.put_nowait(event)
        except asyncio.QueueFull:
            # drop-oldest: best-effort delivery must not back-pressure the
            # status loops (bounded channel semantics of the reference)
            try:
                self.queue.get_nowait()
                self.dropped += 1
                self.queue.put_nowait(event)
            except asyncio.QueueEmpty:
                pass

    def handle_status_change(self, address: str, old_status: str, new_status: str) -> None:
        self.emit(
            "node_status_changed",
            address=address,
            old_status=old_status,
            new_status=new_status,
        )

    def handle_group_created(self, group_dict: dict) -> None:
        self.emit("group_created", group=group_dict)

    def handle_group_destroyed(self, group_dict: dict) -> None:
        self.emit("group_destroyed", group=group_dict)

    # ----- delivery -----

    async def drain_once(self) -> int:
        """Deliver everything currently queued (tests tick this)."""
        n = 0
        while not self.queue.empty():
            event = self.queue.get_nowait()
            for cfg in self.configs:
                if cfg.event_types and event["type"] not in cfg.event_types:
                    continue
                try:
                    async with self.http.post(cfg.url, json=event) as resp:
                        if resp.status < 400:
                            self.delivered += 1
                except Exception:
                    continue
            n += 1
        return n

    async def run(self, interval: float = 1.0) -> None:
        while True:
            await self.drain_once()
            await asyncio.sleep(interval)

    def start(self) -> None:
        self._drainer = asyncio.get_running_loop().create_task(self.run())

    def stop(self) -> None:
        if self._drainer:
            self._drainer.cancel()
