"""Scheduling layer.

``Scheduler`` reproduces the reference's per-heartbeat matcher surface
(crates/orchestrator/src/scheduler/mod.rs): fetch tasks -> plugin filter
chain -> pick -> expand variables. Two interchangeable backends:

  greedy  - the reference's behavior exactly (first task after filters);
            the parity oracle and fallback path.
  tpu     - batch matcher: encodes the whole marketplace, solves one
            assignment problem on the accelerator (auction kernel), serves
            per-node lookups from the cached batch solution, re-solving when
            the node/task population changes.
"""

from protocol_tpu.sched.scheduler import Scheduler, expand_task_for_node
from protocol_tpu.sched.tpu_backend import TpuBatchMatcher

__all__ = ["Scheduler", "TpuBatchMatcher", "expand_task_for_node"]
