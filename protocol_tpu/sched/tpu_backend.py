"""TPU batch matcher: the ``scheduler_backend=tpu`` hot path.

Replaces the reference's per-heartbeat O(tasks) greedy walk
(crates/orchestrator/src/scheduler/mod.rs:26-74) with one batched solve per
population change: encode every schedulable node and every task once,
build the cost tensor on-device, and resolve contention with the auction
kernel. Per-heartbeat lookups then hit a host-side dict.

Task semantics: the reference's matcher hands the *same* (newest) task to
every node — tasks are unbounded swarms. This framework generalizes with a
``replicas`` bound read from the task's scheduling config
(``plugins["tpu_scheduler"]["replicas"] = ["<N>"]``; absent = unbounded,
matching the reference). Requirements come from
``plugins["tpu_scheduler"]["compute_requirements"] = ["<DSL>"]`` in the same
requirements DSL the pools use (shared/src/models/node.rs:180-374).

Solve structure:
  - bounded tasks are unit-expanded into replica slots -> auction over
    [nodes x slots] (contended, price-mediated);
  - unassigned nodes then take their cheapest compatible unbounded task
    (row argmin — contention-free, exactly the swarm semantics).

Shapes are padded to power-of-two buckets so jit re-traces only on bucket
growth, not on every membership change.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from protocol_tpu.models.node import ComputeRequirements
from protocol_tpu.models.task import Task
from protocol_tpu.ops.assign import assign_auction
from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
from protocol_tpu.ops.encoding import FeatureEncoder
from protocol_tpu.store.context import StoreContext
from protocol_tpu.store.domains.node_store import NodeStatus, OrchestratorNode

SCHEDULABLE = (NodeStatus.HEALTHY, NodeStatus.WAITING_FOR_HEARTBEAT)

_PROFILE_LOCK = threading.Lock()  # jax.profiler.trace is process-global


def _pow2_bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def task_replicas(task: Task) -> Optional[int]:
    cfg = task.scheduling_config
    if cfg and cfg.plugins:
        vals = cfg.plugins.get("tpu_scheduler", {}).get("replicas")
        if vals:
            r = int(vals[0])
            if r <= 0:
                raise ValueError(f"replicas must be positive, got {r}")
            return r
    return None


def task_requirements(task: Task) -> ComputeRequirements:
    cfg = task.scheduling_config
    if cfg and cfg.plugins:
        vals = cfg.plugins.get("tpu_scheduler", {}).get("compute_requirements")
        if vals:
            return ComputeRequirements.parse(vals[0])
    return ComputeRequirements()


def validate_tpu_scheduler_config(task: Task) -> None:
    """Reject malformed tpu_scheduler plugin config at task-creation time so
    user input can never break the batch solve (raises ValueError)."""
    try:
        task_replicas(task)
        task_requirements(task)
    except Exception as e:
        raise ValueError(f"invalid tpu_scheduler config: {e}") from e


@jax.jit
def _solve_bounded(ep, er, weights) -> jax.Array:
    cost, _ = cost_matrix(ep, er, weights)
    return assign_auction(cost, eps=0.05, max_iters=300).task_for_provider


@jax.jit
def _cost_only(ep, er, weights) -> jax.Array:
    return cost_matrix(ep, er, weights)[0]


@jax.jit
def _solve_unbounded(ep, er, weights) -> tuple[jax.Array, jax.Array]:
    cost, _ = cost_matrix(ep, er, weights)
    best = jnp.argmin(cost, axis=1).astype(jnp.int32)  # [P]
    feas = jnp.take_along_axis(cost, best[:, None], axis=1)[:, 0] < INFEASIBLE * 0.5
    return jnp.where(feas, best, -1), feas


class TpuBatchMatcher:
    def __init__(
        self,
        store: StoreContext,
        weights: Optional[CostWeights] = None,
        min_solve_interval: float = 1.0,
        max_replica_slots: int = 4096,
        native_fallback: bool = False,
        time_fn=time.monotonic,
    ):
        self.store = store
        self.weights = weights or CostWeights(priority=1.0)
        self.min_solve_interval = min_solve_interval
        self.max_replica_slots = max_replica_slots
        # degraded mode: solve with the native C++ engine instead of the
        # jitted kernels (for deployments whose accelerator is absent or
        # unreachable — the engine is this framework's CPU backend, not an
        # external dependency). Opt-in so tests keep covering the jax path.
        self.native_fallback = native_fallback
        if native_fallback:
            # pin the process to the host platform NOW: the whole point is
            # an unreachable accelerator, and letting jax initialize the
            # remote platform on first use would hang the solve path
            jax.config.update("jax_platforms", "cpu")
        self._time = time_fn
        self._dirty = True
        self._last_solve = float("-inf")
        self._assignment: dict[str, str] = {}  # node address -> task id
        self._covered: set[str] = set()  # addresses the last solve considered
        # heartbeats arrive from worker threads (asyncio.to_thread): one lock
        # serializes solves and makes (_assignment, _covered) swaps atomic
        self._solve_lock = threading.Lock()
        self.encoder = FeatureEncoder()
        self.last_solve_stats: dict = {}
        self._solve_seq = 0

    # ----- invalidation hooks (wire to TaskStore observers + node changes)

    def mark_dirty(self) -> None:
        self._dirty = True

    def attach_observers(self) -> None:
        self.store.task_store.subscribe_created(lambda t: self.mark_dirty())
        self.store.task_store.subscribe_deleted(lambda t: self.mark_dirty())

    # ----- lookup

    def lookup(self, node: OrchestratorNode) -> tuple[Optional[Task], bool]:
        """Returns (task, covered). ``covered`` means the last batch solve
        considered this node, so an empty assignment is a deliberate verdict
        (infeasible or capacity-excluded), not a gap to paper over."""
        self._ensure_fresh()
        covered = node.address in self._covered
        tid = self._assignment.get(node.address)
        task = self.store.task_store.get_task(tid) if tid else None
        return task, covered

    def task_for_node(self, node: OrchestratorNode) -> Optional[Task]:
        return self.lookup(node)[0]

    def _ensure_fresh(self) -> None:
        # Re-solve only when something changed, and never more often than
        # min_solve_interval — population churn must not turn back into a
        # per-heartbeat O(solve) cost. The lock keeps concurrent heartbeat
        # threads from solving twice or observing a half-swapped assignment.
        if self._dirty and self._time() - self._last_solve >= self.min_solve_interval:
            with self._solve_lock:
                if self._dirty and (
                    self._time() - self._last_solve >= self.min_solve_interval
                ):
                    self.refresh()

    # ----- device solves (overridden by RemoteBatchMatcher to route the
    # same columnar batches through the gRPC scheduler backend)

    def _native_cost(self, ep, er) -> np.ndarray:
        # module-level jit: re-traces per shape bucket, not per solve
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return np.asarray(_cost_only(ep, er, self.weights))

    def _bounded_t4p(self, ep, er) -> np.ndarray:
        if self.native_fallback:
            from protocol_tpu import native

            cost = self._native_cost(ep, er)
            n_providers, _n_slots = cost.shape
            cand_p, cand_c = native.topk_candidates(cost, k=min(64, n_providers))
            p4s = native.auction_sparse(cand_p, cand_c, num_providers=n_providers)
            t4p = np.full(n_providers, -1, np.int32)
            for s_idx, p_idx in enumerate(p4s):
                if p_idx >= 0:
                    t4p[p_idx] = s_idx
            return t4p
        return np.asarray(_solve_bounded(ep, er, self.weights))

    def _unbounded_best(self, ep, er) -> np.ndarray:
        if self.native_fallback:
            cost = self._native_cost(ep, er)
            best = cost.argmin(axis=1).astype(np.int32)
            feas = cost[np.arange(cost.shape[0]), best] < INFEASIBLE * 0.5
            return np.where(feas, best, -1).astype(np.int32)
        best, _feas = _solve_unbounded(ep, er, self.weights)
        return np.asarray(best)

    # ----- batch solve

    def refresh(self) -> None:
        """One batch solve; with PROTOCOL_TPU_PROFILE_DIR set, each solve
        is captured as an xprof trace (SURVEY §5's stated tracing plan:
        JAX profiler instead of the reference's log-line timing)."""
        profile_dir = os.environ.get("PROTOCOL_TPU_PROFILE_DIR", "")
        if profile_dir:
            # jax.profiler.trace is process-global and cannot nest: one
            # lock across ALL matcher instances (devnet runs several)
            with _PROFILE_LOCK, jax.profiler.trace(profile_dir):
                self._refresh()
            return
        self._refresh()

    def _refresh(self) -> None:
        t_start = time.perf_counter()
        # clear the dirty flag BEFORE reading state: a concurrent mark_dirty
        # landing mid-read must trigger another solve, not be erased
        self._dirty = False
        self._last_solve = self._time()
        nodes = [
            n for n in self.store.node_store.get_nodes() if n.status in SCHEDULABLE
        ]
        tasks = self.store.task_store.get_all_tasks()
        # Drop tasks with malformed plugin config (validated at creation via
        # validate_tpu_scheduler_config; this guards direct store writes).
        ok_tasks = []
        for t in tasks:
            try:
                task_replicas(t)
                task_requirements(t)
            except Exception:
                continue
            ok_tasks.append(t)
        tasks = ok_tasks
        # build the new solution locally and swap at the end so concurrent
        # readers never observe a half-built assignment
        assignment: dict[str, str] = {}
        covered = {n.address for n in nodes}
        if not nodes or not tasks:
            self._assignment, self._covered = assignment, covered
            self._solve_seq += 1
            self.last_solve_stats = {
                "nodes": len(nodes),
                "tasks": len(tasks),
                "seq": self._solve_seq,
            }
            return

        # newest-first priority, matching NewestTaskPlugin ordering:
        # normalize created_at to [0, 1] so the priority cost term dominates
        # ties in the same direction as the reference's sort.
        created = np.asarray([t.created_at for t in tasks], np.float64)
        span = max(created.max() - created.min(), 1.0)
        prio = ((created - created.min()) / span).astype(np.float32)

        bounded: list[tuple[int, int]] = []  # (task idx, replicas)
        unbounded: list[int] = []
        for i, t in enumerate(tasks):
            r = task_replicas(t)
            if r is None:
                unbounded.append(i)
            else:
                bounded.append((i, r))

        specs = [n.compute_specs for n in nodes]
        locs = [n.location for n in nodes]
        P = len(nodes)
        p_bucket = _pow2_bucket(P)
        ep = self.encoder.encode_providers(specs, locations=locs, pad_to=p_bucket)

        assigned = np.zeros(P, bool)
        truncated_slots = 0

        # ---- phase 1: bounded tasks -> replica slots -> auction
        if bounded:
            req_by_task = {i: task_requirements(tasks[i]) for i, _ in bounded}
            slot_task: list[int] = []
            for i, r in bounded:
                take = min(
                    min(r, P), self.max_replica_slots - len(slot_task)
                )
                slot_task.extend([i] * take)
                if len(slot_task) >= self.max_replica_slots:
                    break
            # arithmetic, not loop iterations: demand can be ~1M slots
            truncated_slots = sum(min(r, P) for _, r in bounded) - len(slot_task)
            if truncated_slots:
                # never a silent cap: at 1M-scale demand, dropped replica
                # slots are a capacity decision the operator must see
                logging.getLogger(__name__).warning(
                    "replica demand exceeds max_replica_slots=%d: "
                    "%d slots dropped this solve",
                    self.max_replica_slots,
                    truncated_slots,
                )
            reqs = [req_by_task[i] for i in slot_task]
            prios = [prio[i] for i in slot_task]
            s_bucket = _pow2_bucket(len(slot_task))
            er = self.encoder.encode_requirements(
                reqs, priorities=prios, pad_to=s_bucket
            )
            t4p = self._bounded_t4p(ep, er)[:P]
            for p_idx, s_idx in enumerate(t4p):
                if s_idx >= 0 and s_idx < len(slot_task):
                    assignment[nodes[p_idx].address] = tasks[slot_task[s_idx]].id
                    assigned[p_idx] = True

        # ---- phase 2: remaining nodes -> cheapest compatible unbounded task
        if unbounded and not assigned.all():
            reqs = [task_requirements(tasks[i]) for i in unbounded]
            prios = [prio[i] for i in unbounded]
            t_bucket = _pow2_bucket(len(unbounded))
            er = self.encoder.encode_requirements(
                reqs, priorities=prios, pad_to=t_bucket
            )
            best = self._unbounded_best(ep, er)[:P]
            for p_idx in range(P):
                if not assigned[p_idx] and best[p_idx] >= 0 and best[p_idx] < len(unbounded):
                    assignment[nodes[p_idx].address] = tasks[unbounded[best[p_idx]]].id

        self._assignment, self._covered = assignment, covered
        self._solve_seq += 1
        self.last_solve_stats = {
            "nodes": P,
            "tasks": len(tasks),
            "bounded_tasks": len(bounded),
            "assigned": len(assignment),
            "solve_ms": (time.perf_counter() - t_start) * 1e3,
            "truncated_replica_slots": truncated_slots,
            "seq": self._solve_seq,  # monotone id for scrape-side dedup
        }
