"""TPU batch matcher: the ``scheduler_backend=tpu`` hot path.

Replaces the reference's per-heartbeat O(tasks) greedy walk
(crates/orchestrator/src/scheduler/mod.rs:26-74) with one batched solve per
population change: encode every schedulable node and every task once,
build the cost tensor on-device, and resolve contention with the auction
kernel. Per-heartbeat lookups then hit a host-side dict.

Task semantics: the reference's matcher hands the *same* (newest) task to
every node — tasks are unbounded swarms. This framework generalizes with a
``replicas`` bound read from the task's scheduling config
(``plugins["tpu_scheduler"]["replicas"] = ["<N>"]``; absent = unbounded,
matching the reference). Requirements come from
``plugins["tpu_scheduler"]["compute_requirements"] = ["<DSL>"]`` in the same
requirements DSL the pools use (shared/src/models/node.rs:180-374).

Solve structure:
  - bounded tasks are unit-expanded into replica slots -> auction over
    [nodes x slots] (contended, price-mediated);
  - unassigned nodes then take their cheapest compatible unbounded task
    (row argmin — contention-free, exactly the swarm semantics).

Shapes are padded to power-of-two buckets so jit re-traces only on bucket
growth, not on every membership change.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

# must precede this module's @jax.jit decorators (the ops import below
# also installs it; stated here because this file jits directly)
from protocol_tpu.utils import jitwitness as _jitwitness

_jitwitness.install()

from protocol_tpu.models.node import ComputeRequirements
from protocol_tpu.models.task import Task
from protocol_tpu.ops.assign import assign_auction
from protocol_tpu.ops.cost import (
    INFEASIBLE,
    CostWeights,
    cost_matrix,
    with_tie_jitter,
)
from protocol_tpu.ops.encoding import FeatureEncoder
from protocol_tpu.ops.sparse import (
    assign_auction_sparse_scaled,
    assign_auction_sparse_warm,
    candidates_topk,
)
from protocol_tpu.sched.cand_cache import (
    CandidateCache,
    CandidateMemo,
    ProviderItem,
    TaskItem,
)
from protocol_tpu.store.context import StoreContext
from protocol_tpu.store.domains.node_store import NodeStatus, OrchestratorNode

SCHEDULABLE = (NodeStatus.HEALTHY, NodeStatus.WAITING_FOR_HEARTBEAT)

from protocol_tpu.utils.lockwitness import LazyLock, make_lock

# LazyLock: module-global (the witness decision must wait for first use);
# jax.profiler.trace is process-global
_PROFILE_LOCK = LazyLock("profile")


def _pow2_bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def task_replicas(task: Task) -> Optional[int]:
    cfg = task.scheduling_config
    if cfg and cfg.plugins:
        vals = cfg.plugins.get("tpu_scheduler", {}).get("replicas")
        if vals:
            r = int(vals[0])
            if r <= 0:
                raise ValueError(f"replicas must be positive, got {r}")
            return r
    return None


def task_requirements(task: Task) -> ComputeRequirements:
    cfg = task.scheduling_config
    if cfg and cfg.plugins:
        vals = cfg.plugins.get("tpu_scheduler", {}).get("compute_requirements")
        if vals:
            return ComputeRequirements.parse(vals[0])
    return ComputeRequirements()


def task_anti_affinity(task: Task) -> Optional[str]:
    """Replica-spread constraint (BASELINE ladder #5's anti-affinity term):
    ``"task"`` = replicas on distinct providers (the matching already
    guarantees this; declared form documents intent), ``"location"`` =
    replicas on distinct geographic locations (failure-domain spread the
    reference cannot express — its matcher hands every node the same
    task, scheduler/mod.rs:26-74)."""
    cfg = task.scheduling_config
    if cfg and cfg.plugins:
        vals = cfg.plugins.get("tpu_scheduler", {}).get("anti_affinity")
        if vals:
            mode = str(vals[0])
            if mode not in ("task", "location"):
                raise ValueError(f"anti_affinity must be task|location, got {mode!r}")
            return mode
    return None


def task_colocate(task: Task) -> bool:
    """Capacity-sharing opt-in (BASELINE ladder #5's core semantics:
    "several tasks land on one provider while capacity holds"). Colocated
    task replicas route through the vector bin-pack (ops/binpack.py) over
    the providers' real multi-resource capacity (GPU count, total VRAM,
    cpu cores, ram, storage) instead of the one-task-per-provider auction
    — a 2-GPU provider can hold two 1-GPU tasks concurrently. The
    reference cannot express this at all (one node, one task:
    crates/orchestrator/src/scheduler/mod.rs:26-74)."""
    cfg = task.scheduling_config
    if cfg and cfg.plugins:
        vals = cfg.plugins.get("tpu_scheduler", {}).get("colocate")
        if vals:
            v = str(vals[0]).lower()
            if v not in ("true", "false"):
                raise ValueError(f"colocate must be true|false, got {vals[0]!r}")
            return v == "true"
    return False


def validate_tpu_scheduler_config(task: Task) -> None:
    """Reject malformed tpu_scheduler plugin config at task-creation time so
    user input can never break the batch solve (raises ValueError)."""
    try:
        replicas = task_replicas(task)
        task_requirements(task)
        if task_anti_affinity(task) is not None and replicas is None:
            raise ValueError(
                "anti_affinity requires a replicas bound (unbounded swarm "
                "tasks have no replica set to spread)"
            )
        if task_colocate(task):
            if replicas is None:
                raise ValueError(
                    "colocate requires a replicas bound (the capacity "
                    "bin-pack places a finite replica set)"
                )
            if task_anti_affinity(task) is not None:
                raise ValueError(
                    "colocate and anti_affinity are mutually exclusive "
                    "(stacking vs spreading)"
                )
    except Exception as e:
        raise ValueError(f"invalid tpu_scheduler config: {e}") from e


@jax.jit
def _solve_bounded(ep, er, weights) -> jax.Array:
    cost, _ = cost_matrix(ep, er, weights)
    # with_tie_jitter: without it, identically-specced providers make every
    # open slot bid the SAME provider each round — one assignment per
    # round, so the solve seats exactly max_iters replicas (observed
    # 300/400 live)
    return assign_auction(
        with_tie_jitter(cost), eps=0.05, max_iters=300
    ).task_for_provider


@jax.jit
def _cost_only(ep, er, weights) -> jax.Array:
    return cost_matrix(ep, er, weights)[0]


@jax.jit
def _solve_unbounded(ep, er, weights) -> tuple[jax.Array, jax.Array]:
    cost, _ = cost_matrix(ep, er, weights)
    best = jnp.argmin(cost, axis=1).astype(jnp.int32)  # [P]
    feas = jnp.take_along_axis(cost, best[:, None], axis=1)[:, 0] < INFEASIBLE * 0.5
    return jnp.where(feas, best, -1), feas


class TpuBatchMatcher:
    # the candidate cache is an in-process structure; RemoteBatchMatcher
    # (whose candidates live behind the gRPC seam) turns it off
    use_candidate_cache = True

    def __init__(
        self,
        store: StoreContext,
        weights: Optional[CostWeights] = None,
        min_solve_interval: float = 1.0,
        max_replica_slots: int = 1 << 20,
        dense_cell_budget: int = 1 << 24,
        top_k: int = 64,
        warm_start: bool = True,
        native_fallback: bool = False,
        native_engine: str = "native",
        native_threads: int = 0,
        use_mesh: bool = False,
        approx_recall: Optional[float] = None,
        time_fn=time.monotonic,
    ):
        self.store = store
        self.weights = weights or CostWeights(priority=1.0)
        self.min_solve_interval = min_solve_interval
        self.max_replica_slots = max_replica_slots
        # [providers x slots] cost cells above which phase 1 switches from
        # the dense auction to the streaming top-K + sparse frontier auction
        # (the only viable shape at 1M scale — ops/sparse.py). 2^24 cells =
        # 64 MB f32: comfortably dense below, pointlessly so above.
        self.dense_cell_budget = dense_cell_budget
        self.top_k = top_k
        # carry auction prices + the previous matching across solves so
        # population churn re-bids only the delta frontier (SURVEY §7 hard
        # part 4) instead of cold-solving the full population
        self.warm_start = warm_start
        self._warm_price_by_addr: dict[str, float] = {}
        # retirement mask carried between warm solves, keyed to the slot
        # layout it was computed under (see _solve_slots_cached)
        self._warm_retired: np.ndarray | None = None
        self._warm_retired_fp: tuple | None = None
        # claim-masked slot rows (anti-affinity/colocation) of the current
        # and previous solve: both dirty the carried retirement mask
        self._claim_rows_now: np.ndarray | None = None
        self._claim_rows_prev: np.ndarray | None = None
        # forward auctions never LOWER prices: carried prices ratchet
        # within a warm chain. Three bounds keep that safe: the warm
        # kernel caps entry prices below its retirement floor
        # (ops/sparse.py assign_auction_sparse_warm), the CandidateCache
        # rebuilds ADAPTIVELY when measured base drift has re-ranked more
        # than max_stale_frac of the fleet (cand_cache._stale_fraction —
        # staleness bounded by measurement, not schedule), and
        # ``cold_every`` remains the schedule BACKSTOP for drift the
        # measurement can't see (e.g. price ratchet on the uncached wire
        # path, which has no selection cache to measure).
        self.cold_every = 256
        self._warm_solves_since_cold = 0
        # degraded mode: solve with the native C++ engine instead of the
        # jitted kernels (for deployments whose accelerator is absent or
        # unreachable — the engine is this framework's CPU backend, not an
        # external dependency). Opt-in so tests keep covering the jax path.
        self.native_fallback = native_fallback
        # native engine selection: "native" is the single-threaded
        # Gauss-Seidel engine; "native-mt" runs the multi-threaded fused
        # pass + deterministic Jacobi auction THROUGH the persistent solve
        # arena (protocol_tpu/native/arena.py), so steady-state solves
        # recompute only churned rows; "sinkhorn-mt" rides the same arena
        # but solves with the O(nnz) sparse entropic engine (warm (f, g)
        # potential carry + auction-referee rounding) — the soft/
        # relaxation twin the combinatorial solver is refereed against.
        # native_threads: 0 = all hardware threads. "jax[:D]" selects
        # the accelerator-path warm arena (parallel/jax_arena.py) as a
        # PEER of the native engines — same persistent-arena semantics,
        # sharded candidate generation over D devices (0/absent = all
        # visible). It is not gated on native_fallback: under fallback
        # the process is pinned to CPU and the jax engine runs there,
        # single-device — degraded inside the engine, never silently
        # swapped for a native one.
        self._jax_devices = 0
        if native_engine.partition(":")[0] == "jax":
            suffix = native_engine.partition(":")[2]
            try:
                self._jax_devices = int(suffix) if suffix else 0
            except ValueError:
                raise ValueError(
                    f"bad jax device suffix in {native_engine!r} "
                    "(want jax[:D])"
                )
        elif native_engine not in ("native", "native-mt", "sinkhorn-mt"):
            raise ValueError(
                "native_engine must be native|native-mt|sinkhorn-mt|"
                f"jax[:D], got {native_engine!r}"
            )
        self.native_engine = native_engine
        self._jax_engine = native_engine.partition(":")[0] == "jax"
        self.native_threads = int(native_threads)
        self._native_arena = None
        self._last_arena_stats: dict = {}
        # multi-chip solves: route phase 1's eps-ladder / warm kernels
        # through the task-sharded mesh variants (parallel/sparse.py, the
        # v5e-8 path) when more than one device is visible. Opt-in
        # (deploy sets PROTOCOL_TPU_USE_MESH=1 via serve): the sharded
        # frontier schedule is a different — equally valid — auction
        # order, and single-chip deployments gain nothing from it.
        self.use_mesh = use_mesh
        # stage-A selection via lax.approx_max_k (TPU PartialReduce)
        # instead of exact lax.top_k — the measured stage-A bottleneck's
        # mitigation (SCALING.md); e.g. 0.95. None = exact.
        self.approx_recall = approx_recall
        self._mesh = None
        self._last_sharded = False
        self._last_gen_sharded = False
        self._mesh_fallback_logged = False
        if native_fallback:
            # pin the process to the host platform NOW: the whole point is
            # an unreachable accelerator, and letting jax initialize the
            # remote platform on first use would hang the solve path.
            # MUST precede the mesh probe below — jax.devices() initializes
            # the default backend, which is exactly the hang being avoided.
            jax.config.update("jax_platforms", "cpu")
        if use_mesh and not native_fallback:
            import jax as _jax

            if len(_jax.devices()) > 1:
                from protocol_tpu.parallel import make_mesh

                self._mesh = make_mesh(len(_jax.devices()))
            else:
                logging.getLogger(__name__).warning(
                    "use_mesh requested but only one device is visible; "
                    "solving single-device"
                )
        self._time = time_fn
        self._dirty = True
        self._last_solve = float("-inf")
        self._assignment: dict[str, str] = {}  # node address -> task id
        # colocated nodes hold SEVERAL tasks concurrently (phase 0.5
        # capacity bin-pack); _assignment keeps the first for the
        # one-task lookup surface, this holds the full ordered list
        self._assignment_multi: dict[str, list[str]] = {}
        self._covered: set[str] = set()  # addresses the last solve considered
        # heartbeats arrive from worker threads (asyncio.to_thread): one lock
        # serializes solves and makes (_assignment, _covered) swaps atomic
        self._solve_lock = make_lock("solve")
        self.encoder = FeatureEncoder()
        self._cache = CandidateCache(self.encoder, self.weights, k=top_k)
        # content-hash memo for the UNCACHED wire path (stateless repeats)
        self._cand_memo = CandidateMemo()
        self._last_warm_used = False
        self._last_warm_seeded = 0
        self._last_stall: dict = {}
        # flight recorder (PROTOCOL_TPU_TRACE=<path>): the native-arena
        # solve path records its exact encoded inputs + matching, so any
        # live or bench run yields a replayable trace
        # (protocol_tpu/trace/). Lazy: the trace package (and its pb2
        # import) loads only when capture is requested.
        self.trace_recorder = None
        if os.environ.get("PROTOCOL_TPU_TRACE"):
            from protocol_tpu.trace.recorder import TraceRecorder

            self.trace_recorder = TraceRecorder.from_env("matcher")
        self._groups_plugin = None
        self._group_assignment: dict[str, str] = {}  # group id -> task id
        self._group_covered: set[str] = set()
        self.last_solve_stats: dict = {}
        self._solve_seq = 0

    # ----- invalidation hooks (wire to TaskStore observers + node changes)

    def mark_dirty(self) -> None:
        self._dirty = True

    def attach_observers(self) -> None:
        self.store.task_store.subscribe_created(lambda t: self.mark_dirty())
        self.store.task_store.subscribe_deleted(lambda t: self.mark_dirty())

    def attach_groups(self, plugin) -> None:
        """Compose with a NodeGroupsPlugin (SURVEY §7 hard part 5): grouped
        nodes leave the individual solve (their work arrives group-wise),
        groups become pseudo-providers in a topology-masked cost solve, and
        the plugin's group<->task selection goes through
        :meth:`rank_task_for_group` instead of ``rng.choice`` — while ALL
        of the plugin's race-safe commit machinery (SET-NX group task,
        compare-and-delete cleanup, dissolved-group recovery) stays in
        charge of the actual assignment."""
        self._groups_plugin = plugin
        plugin.task_ranker = self.rank_task_for_group
        for hook_name in ("on_group_created", "on_group_dissolved"):
            prev = getattr(plugin, hook_name)

            def chained(group, prev=prev):
                self.mark_dirty()
                if prev is not None:
                    prev(group)

            setattr(plugin, hook_name, chained)

    # ----- lookup

    def lookup(self, node: OrchestratorNode) -> tuple[Optional[Task], bool]:
        """Returns (task, covered). ``covered`` means the last batch solve
        considered this node, so an empty assignment is a deliberate verdict
        (infeasible or capacity-excluded), not a gap to paper over."""
        self._ensure_fresh()
        covered = node.address in self._covered
        tid = self._assignment.get(node.address)
        task = self.store.task_store.get_task(tid) if tid else None
        return task, covered

    def task_for_node(self, node: OrchestratorNode) -> Optional[Task]:
        return self.lookup(node)[0]

    def assigned_task_ids(self, address: str) -> list[str]:
        """Multi-assignment ids from the LAST solve, no refresh — a plain
        dict read for callers that already resolved the node this beat
        (the heartbeat path calls get_task_for_node first). [] for
        non-colocated nodes."""
        return list(self._assignment_multi.get(address, ()))

    def tasks_for_node(self, node: OrchestratorNode) -> list[Task]:
        """ALL tasks assigned to this node in the last solve: one for
        auction/unbounded nodes, several for colocated nodes (ladder #5
        capacity sharing). Order is placement order — the first entry is
        what the one-task ``lookup`` surface serves."""
        self._ensure_fresh()
        tids = self._assignment_multi.get(node.address)
        if not tids:
            task, _ = self.lookup(node)
            return [task] if task is not None else []
        found = (self.store.task_store.get_task(t) for t in tids)
        return [t for t in found if t is not None]

    def _ensure_fresh(self) -> None:
        # Re-solve only when something changed, and never more often than
        # min_solve_interval — population churn must not turn back into a
        # per-heartbeat O(solve) cost. The lock keeps concurrent heartbeat
        # threads from solving twice or observing a half-swapped assignment.
        if self._dirty and self._time() - self._last_solve >= self.min_solve_interval:
            with self._solve_lock:
                if self._dirty and (
                    self._time() - self._last_solve >= self.min_solve_interval
                ):
                    self.refresh()

    # ----- device solves (overridden by RemoteBatchMatcher to route the
    # same columnar batches through the gRPC scheduler backend)

    def _native_cost(self, ep, er) -> np.ndarray:
        # module-level jit: re-traces per shape bucket, not per solve
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return np.asarray(_cost_only(ep, er, self.weights))

    def _bounded_t4p(self, ep, er) -> np.ndarray:
        if self._jax_engine:
            # the accelerator-path peer of the native arenas: persistent
            # candidate structure + warm auction duals, sharded gen over
            # the device mesh — checked BEFORE native_fallback so a
            # CPU-pinned process still runs the jax engine (on CPU
            # devices), never a silent native swap
            n_providers = int(np.asarray(ep.gpu_count).shape[0])
            if self._native_arena is None:
                from protocol_tpu.parallel.jax_arena import JaxSolveArena

                self._native_arena = JaxSolveArena(
                    cold_every=self.cold_every,
                    devices=self._jax_devices,
                    approx_recall=self.approx_recall,
                )
            p4s = self._native_arena.solve(ep, er, self.weights)
            self._last_arena_stats = {
                f"arena_{k}": v
                for k, v in self._native_arena.last_stats.items()
            }
            if self.trace_recorder is not None:
                from protocol_tpu.trace.recorder import safe as _trace_safe

                _trace_safe(
                    self.trace_recorder.record_solve, ep, er,
                    self.weights, self.native_engine,
                    self._native_arena.k, self._native_arena.eps_end,
                    0, p4s, self._native_arena.price,
                    metrics=dict(self._last_arena_stats),
                )
            t4p = np.full(n_providers, -1, np.int32)
            for s_idx, p_idx in enumerate(p4s):
                if p_idx >= 0:
                    t4p[p_idx] = s_idx
            return t4p
        if self.native_fallback:
            from protocol_tpu import native

            n_providers = int(np.asarray(ep.gpu_count).shape[0])
            self._last_arena_stats = {}
            if self.native_engine in ("native-mt", "sinkhorn-mt"):
                # persistent warm-solve arena: candidate structure, solver
                # duals (auction prices+retirement, or sinkhorn potentials)
                # survive between solves; only churned rows are recomputed
                # (tentpole semantics of the CandidateCache, on the native
                # path)
                if self._native_arena is None:
                    from protocol_tpu.native.arena import NativeSolveArena

                    self._native_arena = NativeSolveArena(
                        threads=self.native_threads,
                        cold_every=self.cold_every,
                        engine=(
                            "sinkhorn"
                            if self.native_engine == "sinkhorn-mt"
                            else "auction"
                        ),
                    )
                p4s = self._native_arena.solve(ep, er, self.weights)
                self._last_arena_stats = {
                    f"arena_{k}": v
                    for k, v in self._native_arena.last_stats.items()
                }
                if self.trace_recorder is not None:
                    from protocol_tpu.trace.recorder import (
                        safe as _trace_safe,
                    )

                    kernel = self.native_engine + (
                        f":{self.native_threads}"
                        if self.native_threads else ""
                    )
                    _trace_safe(
                        self.trace_recorder.record_solve, ep, er,
                        self.weights, kernel, self._native_arena.k,
                        self._native_arena.eps_end, 0, p4s,
                        self._native_arena.price,
                        metrics=dict(self._last_arena_stats),
                    )
            else:
                # fused feature->cost->top-k: the [P, T] tensor never
                # exists (same streaming shape as the sparse TPU path)
                cand_p, cand_c = native.fused_topk_candidates(
                    ep, er, self.weights, k=min(64, n_providers)
                )
                p4s = native.auction_sparse(
                    cand_p, cand_c, num_providers=n_providers
                )
            t4p = np.full(n_providers, -1, np.int32)
            for s_idx, p_idx in enumerate(p4s):
                if p_idx >= 0:
                    t4p[p_idx] = s_idx
            return t4p
        return np.asarray(_solve_bounded(ep, er, self.weights))

    def _bounded_t4p_sparse(
        self, ep, er, price0: np.ndarray, p4s0: np.ndarray, warm: bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Phase 1 at scale: streaming top-K candidates + frontier auction
        (ops/sparse.py — the 1M-shape architecture, now the live path above
        dense_cell_budget). Returns (slot per provider [P_pad], prices [P_pad]).

        ``warm=True`` runs the single-phase incremental solve seeded with the
        previous solve's prices + matching; cold solves use the eps-scaling
        ladder."""
        s_bucket = int(np.asarray(er.cpu_cores).shape[0])
        tile = min(1024, s_bucket)  # pow2 buckets: tile always divides
        # bidirectional candidates: reverse (provider->slot) edges keep every
        # provider reachable when forward top-k windows pile onto the same
        # cheap providers (coverage-capped matchings at scale — see
        # ops/sparse.py candidates_topk_reverse). Content-hash memoized:
        # an unchanged fleet between heartbeats skips the O(P*T) pass
        # (the wire path's delta-awareness, VERDICT r4 item 3)
        gen = None
        D = self._mesh.shape["p"] if self._mesh is not None else 0
        if D > 1 and s_bucket % D == 0:
            # generation is the stage where the mesh pays (zero per-round
            # collectives — SCALING.md mesh economics); bit-identical to
            # the single-device generator, so it shares the memo key
            from protocol_tpu.parallel import candidates_topk_bidir_sharded

            tile = min(tile, s_bucket // D)

            def gen(ep_, er_, w_, **kw):
                return candidates_topk_bidir_sharded(
                    ep_, er_, w_, mesh=self._mesh, **kw
                )

        misses_before = self._cand_memo.misses
        cand_p, cand_c = self._cand_memo.get(
            ep, er, self.weights, k=self.top_k, tile=tile,
            reverse_r=8, extra=16, approx_recall=self.approx_recall,
            gen=gen,
        )
        # "sharded generation RAN", not "was configured": a memo hit
        # generated nothing (same actually-engaged semantics as
        # mesh_sharded)
        self._last_gen_sharded = (
            gen is not None and self._cand_memo.misses > misses_before
        )
        num_providers = int(np.asarray(ep.gpu_count).shape[0])
        res, price, _retired = self._sparse_solve(
            cand_p, cand_c, num_providers, warm,
            jnp.asarray(price0), jnp.asarray(p4s0),
        )
        return np.asarray(res.task_for_provider), np.asarray(price)

    def _sparse_solve(self, cand_p, cand_c, num_providers, warm, price0, p4t0,
                      stats_out=None, retired0=None):
        """Phase 1's solve dispatch: warm vs cold ladder, single-device vs
        the task-sharded mesh twins (bit-identical phase discipline —
        parallel/sparse.py) when ``use_mesh`` found devices. Always
        returns (result, prices, retired) — the full dual state, so
        chained warm solves can skip re-fighting priced-out slots
        (ops/sparse.py: retirement carry)."""
        D = self._mesh.shape["p"] if self._mesh is not None else 0
        self._last_sharded = D > 1 and cand_p.shape[0] % D == 0
        if self._last_sharded:
            from protocol_tpu.parallel import (
                assign_auction_sparse_scaled_sharded,
                assign_auction_sparse_warm_sharded,
            )

            if warm:
                return assign_auction_sparse_warm_sharded(
                    cand_p, cand_c, num_providers, self._mesh,
                    price0=price0, p4t0=p4t0, stats_out=stats_out,
                    frontier_ladder=True, retired0=retired0,
                    with_state=True,
                )
            return assign_auction_sparse_scaled_sharded(
                cand_p, cand_c, num_providers, self._mesh,
                stats_out=stats_out, frontier_ladder=True, with_state=True,
            )
        if D > 1 and not self._mesh_fallback_logged:
            # a requested-but-never-engaging mesh must be observable, not
            # indistinguishable from a working one
            self._mesh_fallback_logged = True
            logging.getLogger(__name__).warning(
                "mesh solve requested but slot count %d is not divisible "
                "by the %d-device mesh; solving single-device",
                int(cand_p.shape[0]), D,
            )
        if warm:
            return assign_auction_sparse_warm(
                cand_p, cand_c, num_providers,
                price0=price0, p4t0=p4t0, stats_out=stats_out,
                retired0=retired0, with_state=True,
            )
        return assign_auction_sparse_scaled(
            cand_p, cand_c, num_providers, stats_out=stats_out,
            with_state=True,
        )

    def _seed_slots(
        self, p4s0: np.ndarray, row_of_addr: dict, tasks, bounded, slot_range
    ) -> int:
        """Seat the previous solve's holders back into their task's replica
        slots (indices in ``row_of_addr``'s space). Seeds that no longer
        satisfy eps-CS are evicted by the warm kernel's repair pass — the
        remainder is the delta frontier that actually re-bids."""
        tidx_by_id = {tasks[i].id: i for i, _ in bounded}
        prev_by_task: dict[int, list[int]] = {}
        for addr, tid in self._assignment.items():
            row = row_of_addr.get(addr)
            i = tidx_by_id.get(tid)
            if row is not None and i is not None and i in slot_range:
                prev_by_task.setdefault(i, []).append(row)
        for i, holders in prev_by_task.items():
            start, take = slot_range[i]
            for j, row in enumerate(holders[:take]):
                p4s0[start + j] = row
        return int((p4s0 >= 0).sum())

    def _solve_anti_affinity(
        self, ep, N: int, aa, tasks, prio, idx_addrs, loc_by_addr
    ) -> dict[int, int]:
        """Phase 0: place anti-affinity task replicas via the bin-pack
        kernel (ops/binpack.py) with unit capacity — one replica per
        provider — and exclusion groups over the declared domain:
        providers ("task") or geographic locations ("location").

        Cost stays bounded at scale by solving over the UNION of each
        slot's top-K candidates rather than all N providers. Returns
        {provider row -> task idx}."""
        import dataclasses as _dc

        from protocol_tpu.ops.binpack import assign_binpack_ffd

        results: dict[int, int] = {}
        for mode in ("task", "location"):
            items = [(i, take, m) for (i, take, m) in aa if m == mode]
            if not items:
                continue
            slot_task: list[int] = []
            groups: list[int] = []
            for gi, (i, r, _m) in enumerate(items):
                take = min(r, N, 4096)
                if take < min(r, N):
                    # same never-a-silent-cap rule as the phase-1 slot cap
                    self._aa_truncated += min(r, N) - take
                    logging.getLogger(__name__).warning(
                        "anti-affinity replica demand for task %s capped at "
                        "4096 slots (%d dropped this solve)",
                        tasks[i].id, min(r, N) - take,
                    )
                slot_task.extend([i] * take)
                groups.extend([gi] * take)
            S = len(slot_task)
            if S == 0:
                continue
            s_pad = _pow2_bucket(S)
            er = self.encoder.encode_requirements(
                [task_requirements(tasks[i]) for i in slot_task],
                priorities=[float(prio[i]) for i in slot_task],
                pad_to=s_pad,
            )
            cand_p, _ = candidates_topk(
                ep, er, self.weights, k=self.top_k, tile=min(1024, s_pad)
            )
            rows = np.unique(np.asarray(cand_p))
            rows = rows[rows >= 0].astype(np.int64)
            if rows.size == 0:
                continue
            rpad = _pow2_bucket(len(rows))
            gather = np.concatenate(
                [rows, np.zeros(rpad - len(rows), np.int64)]
            )
            sub_ep = jax.tree.map(
                lambda a: jnp.take(a, jnp.asarray(gather), axis=0), ep
            )
            sub_valid = np.zeros(rpad, bool)
            sub_valid[: len(rows)] = np.asarray(ep.valid)[rows]
            sub_ep = _dc.replace(sub_ep, valid=jnp.asarray(sub_valid))
            cost = np.asarray(_cost_only(sub_ep, er, self.weights)).copy()
            # rows claimed by a previous mode pass are taken
            taken_local = np.isin(rows, np.fromiter(results, np.int64, len(results)))
            cost[: len(rows)][taken_local] = INFEASIBLE
            if mode == "location":
                loc_local, L = self._location_classes(rows, idx_addrs, loc_by_addr)
                loc = np.zeros(rpad, np.int32)
                loc[: len(rows)] = loc_local
            else:
                loc = np.arange(rpad, dtype=np.int32)
                L = rpad
            res = assign_binpack_ffd(
                jnp.asarray(cost),
                jnp.ones((s_pad, 1), jnp.float32),
                jnp.ones((rpad, 1), jnp.float32),
                anti_group=jnp.asarray(
                    np.concatenate(
                        [np.asarray(groups, np.int32),
                         np.full(s_pad - S, -1, np.int32)]
                    )
                ),
                loc_id=jnp.asarray(loc),
                # pow2 buckets: L and G size the jitted [L, G] carry, and
                # unbucketed values would retrace on every population drift
                num_locations=_pow2_bucket(int(L)),
                num_groups=_pow2_bucket(len(items)),
            )
            p4s = np.asarray(res.provider_for_task)[:S]
            for s, r_local in enumerate(p4s):
                if 0 <= r_local < len(rows):
                    results[int(rows[r_local])] = slot_task[s]
        return results

    def _solve_colocation(
        self, ep, N: int, colo, tasks, prio, taken_rows
    ) -> dict[int, list[int]]:
        """Phase 0.5: capacity-sharing placement (ladder #5's core
        semantics, live). Colocate-flagged task replicas route through the
        vector bin-pack (ops/binpack.py) with the providers' REAL
        multi-resource capacity — [gpu count, total VRAM, cpu cores, ram,
        storage] from the encoded columns — so several replicas (of one or
        several tasks) stack on one provider while capacity holds.

        Cost stays bounded at scale the same way as the anti-affinity
        phase: solve over the union of each slot's top-K candidates.
        Returns {provider row -> [task idx, ...]} in placement order."""
        import dataclasses as _dc

        from protocol_tpu.ops.binpack import assign_binpack_ffd

        slot_task: list[int] = []
        for i, r in colo:
            take = min(r, 4096)
            if take < r:
                self._colo_truncated += r - take
                logging.getLogger(__name__).warning(
                    "colocate replica demand for task %s capped at 4096 "
                    "slots (%d dropped this solve)", tasks[i].id, r - take,
                )
            slot_task.extend([i] * take)
        S = len(slot_task)
        self._colo_requested = S
        if S == 0:
            return {}
        s_pad = _pow2_bucket(S)
        reqs = [task_requirements(tasks[i]) for i in slot_task]
        # Compat relaxation for capacity sharing: the DSL's gpu count gate
        # is EXACT (reference node.rs:445-459 parity) — a 1-GPU slice
        # would never match a 2-GPU provider. Colocated slots claim a
        # SLICE, so drop count (and the full-provider total-memory max)
        # from the compat side; the bin-pack's demand vector (built from
        # the ORIGINAL requirement below) enforces the real reservation
        # against remaining capacity. Model/per-GPU-memory gates still
        # bind unchanged.
        relaxed = [
            dataclasses.replace(
                r,
                gpu=[
                    dataclasses.replace(
                        g, count=None, total_memory_max=None
                    )
                    for g in r.gpu
                ],
            )
            for r in reqs
        ]
        er = self.encoder.encode_requirements(
            relaxed,
            priorities=[float(prio[i]) for i in slot_task],
            pad_to=s_pad,
        )
        # bidirectional selection: forward-only top-k would cap the row
        # pool at ~k cheap providers on price-dominated fleets (the same
        # coverage cap candidates_topk_reverse's docstring measures),
        # stranding replicas while feasible providers idle
        cand_p, _ = self._cand_memo.get(
            ep, er, self.weights, k=self.top_k, tile=min(1024, s_pad),
            reverse_r=8, extra=16,
        )
        rows = np.unique(np.asarray(cand_p))
        rows = rows[rows >= 0].astype(np.int64)
        if taken_rows:
            rows = rows[~np.isin(rows, np.fromiter(taken_rows, np.int64))]
        if rows.size == 0:
            return {}
        rpad = _pow2_bucket(len(rows))
        gather = np.concatenate([rows, np.zeros(rpad - len(rows), np.int64)])
        sub_ep = jax.tree.map(
            lambda a: jnp.take(a, jnp.asarray(gather), axis=0), ep
        )
        sub_valid = np.zeros(rpad, bool)
        sub_valid[: len(rows)] = np.asarray(ep.valid)[rows]
        sub_ep = _dc.replace(sub_ep, valid=jnp.asarray(sub_valid))
        cost = np.asarray(_cost_only(sub_ep, er, self.weights))

        # capacity from the encoded provider columns (-1 = unreported = 0:
        # can't host what you don't report)
        pg = np.maximum(np.asarray(sub_ep.gpu_count, np.float32)[:rpad], 0.0)
        pvram = pg * np.maximum(
            np.asarray(sub_ep.gpu_mem_mb, np.float32)[:rpad], 0.0
        )
        pc = np.maximum(np.asarray(sub_ep.cpu_cores, np.float32)[:rpad], 0.0)
        pm = np.maximum(np.asarray(sub_ep.ram_mb, np.float32)[:rpad], 0.0)
        ps = np.maximum(np.asarray(sub_ep.storage_gb, np.float32)[:rpad], 0.0)
        capacity = np.stack([pg, pvram, pc, pm, ps], axis=1)

        # demand from the ORIGINAL (unrelaxed) requirements: this is the
        # reservation the bin-pack subtracts from remaining capacity.
        # With GPU OR-alternatives, compat can match a provider via ANY
        # option while the worker may run the largest — reserve the
        # elementwise MAX across options (over-reserving blocks a
        # placement; under-reserving oversubscribes a provider's GPUs,
        # the strictly worse failure)
        demand = np.zeros((s_pad, 5), np.float32)
        for s, r in enumerate(reqs):
            gcount = vram = 0.0
            for g in r.gpu:
                c = float(g.count or 0)
                if g.total_memory_min is not None:
                    v = float(g.total_memory_min)
                else:
                    v = c * float(g.memory_mb or g.memory_mb_min or 0)
                gcount = max(gcount, c)
                vram = max(vram, v)
            demand[s] = (
                gcount,
                vram,
                float(r.cpu.cores or 0) if r.cpu else 0.0,
                float(r.ram_mb or 0),
                float(r.storage_gb or 0),
            )

        res = assign_binpack_ffd(
            jnp.asarray(cost),
            jnp.asarray(demand),
            jnp.asarray(capacity),
        )
        p4s = np.asarray(res.provider_for_task)[:S]
        placed: dict[int, list[int]] = {}
        for s, r_local in enumerate(p4s):
            if 0 <= r_local < len(rows):
                placed.setdefault(int(rows[r_local]), []).append(slot_task[s])
        return placed

    def _location_classes(
        self, rows: np.ndarray, idx_addrs, loc_by_addr
    ) -> tuple[np.ndarray, int]:
        """Location class id per subset row: nodes sharing a (rounded)
        lat/lon coordinate share a class; nodes without a location are
        each their own failure domain (they cannot be proven co-located,
        so spreading treats them as distinct)."""
        keys = []
        for r in rows:
            loc = loc_by_addr.get(idx_addrs[r]) if r < len(idx_addrs) else None
            if loc is not None:
                keys.append((round(loc.latitude, 3), round(loc.longitude, 3)))
            else:
                keys.append(("solo", int(r)))
        uniq = {k: i for i, k in enumerate(dict.fromkeys(keys))}
        return np.asarray([uniq[k] for k in keys], np.int32), len(uniq)

    def _solve_groups(
        self, groups, tasks, prio
    ) -> tuple[dict[str, str], set[str]]:
        """Group <-> task solve through the real cost machinery.

        Groups become pseudo-providers: aggregate price/load (member means)
        and centroid location feed the same cost_matrix the node solve
        uses, with compatibility supplied as an explicit topology mask
        (group's configuration name in the task's allowed_topologies)
        instead of the spec algebra. Replica-BOUNDED topology tasks are
        unit-expanded and matched with the dense auction — their replica
        count now bounds how many groups run them, which rng.choice could
        never express; unassigned groups then take the best applicable
        unbounded task (topology-matched, or unrestricted — the
        reference's any-group-may-run-it semantics,
        node_groups/mod.rs:1122-1188) by row argmin.

        Returns ({group id -> task id}, covered group ids). The plugin's
        SET-NX machinery commits assignments; this solve only ranks.
        """
        gcov = {g.id for g in groups}
        if not groups or not tasks:
            return {}, gcov
        topo_bounded: list[tuple[int, int]] = []
        pool_unbounded: list[int] = []  # phase-B candidates
        for i, t in enumerate(tasks):
            topos = t.allowed_topologies()
            r = task_replicas(t)
            if topos:
                if r is None:
                    pool_unbounded.append(i)
                else:
                    topo_bounded.append((i, r))
            elif r is None:
                # unrestricted unbounded: any group may run it
                pool_unbounded.append(i)

        G = len(groups)
        g_pad = _pow2_bucket(G)
        prices, loads, locs = [], [], []
        for g in groups:
            members = [self.store.node_store.get_node(a) for a in g.nodes]
            members = [m for m in members if m is not None]
            prices.append(
                float(np.mean([m.price or 0.0 for m in members])) if members else 0.0
            )
            loads.append(
                float(np.mean([m.load or 0.0 for m in members])) if members else 0.0
            )
            with_loc = [m.location for m in members if m.location is not None]
            if with_loc:
                from protocol_tpu.models.node import NodeLocation

                locs.append(
                    NodeLocation(
                        latitude=float(np.mean([l.latitude for l in with_loc])),
                        longitude=float(np.mean([l.longitude for l in with_loc])),
                    )
                )
            else:
                locs.append(None)
        ep_g = self.encoder.encode_providers(
            [None] * G, locations=locs, prices=prices, loads=loads, pad_to=g_pad
        )

        result: dict[str, str] = {}
        taken = np.zeros(G, bool)

        # ---- phase A: replica-bounded topology tasks -> dense auction
        if topo_bounded:
            slot_task: list[int] = []
            for i, r in topo_bounded:
                slot_task.extend([i] * min(r, G, 4096))
            S = len(slot_task)
            s_pad = _pow2_bucket(S)
            er = self.encoder.encode_requirements(
                [ComputeRequirements()] * S,
                priorities=[float(prio[i]) for i in slot_task],
                pad_to=s_pad,
            )
            mask = np.zeros((g_pad, s_pad), bool)
            for s, i in enumerate(slot_task):
                topos = set(tasks[i].allowed_topologies())
                for gi, g in enumerate(groups):
                    mask[gi, s] = g.configuration_name in topos
            cost, _ = cost_matrix(ep_g, er, self.weights, mask=jnp.asarray(mask))
            res = assign_auction(with_tie_jitter(cost), eps=0.05, max_iters=300)
            t4g = np.asarray(res.task_for_provider)[:G]
            for gi, s_idx in enumerate(t4g):
                if 0 <= s_idx < S:
                    result[groups[gi].id] = tasks[slot_task[s_idx]].id
                    taken[gi] = True

        # ---- phase B: remaining groups -> best applicable unbounded task.
        # Topology-restricted tasks outrank unrestricted ones regardless of
        # cost: groups are the ONLY venue a topology task can run, while an
        # unrestricted task also reaches every ungrouped node — letting a
        # newer unrestricted task outbid a topology task would starve the
        # gang workload (observed live before this tiering).
        if pool_unbounded and not taken.all():
            T2 = len(pool_unbounded)
            t_pad = _pow2_bucket(T2)
            er = self.encoder.encode_requirements(
                [ComputeRequirements()] * T2,
                priorities=[float(prio[i]) for i in pool_unbounded],
                pad_to=t_pad,
            )
            mask = np.zeros((g_pad, t_pad), bool)
            for c, i in enumerate(pool_unbounded):
                topos = tasks[i].allowed_topologies()
                for gi, g in enumerate(groups):
                    mask[gi, c] = (not topos) or (g.configuration_name in topos)
            cost, _ = cost_matrix(ep_g, er, self.weights, mask=jnp.asarray(mask))
            cost_np = np.asarray(cost)[:G, :T2]
            is_topo = np.asarray(
                [bool(tasks[i].allowed_topologies()) for i in pool_unbounded]
            )
            # tier the argmin: feasible topo columns first
            tiered = np.where(is_topo[None, :], cost_np, cost_np + INFEASIBLE * 0.25)
            tiered = np.where(cost_np < INFEASIBLE * 0.5, tiered, INFEASIBLE)
            best = tiered.argmin(axis=1)
            feas = tiered[np.arange(G), best] < INFEASIBLE * 0.5
            for gi in range(G):
                if not taken[gi] and feas[gi]:
                    result[groups[gi].id] = tasks[pool_unbounded[best[gi]]].id
        return result, gcov

    def rank_task_for_group(self, group, applicable):
        """The NodeGroupsPlugin's task_ranker hook: serve the group solve's
        choice. A group the solve covered but left unassigned deliberately
        gets None (e.g. a bounded topology task's replica budget went to
        other groups); a group formed after the last solve triggers a
        re-solve."""
        self._ensure_fresh()
        if group.id not in self._group_covered:
            self.mark_dirty()
            self._ensure_fresh()
        tid = self._group_assignment.get(group.id)
        match = next((t for t in applicable if t.id == tid), None)
        if match is not None:
            return match
        if group.id in self._group_covered:
            return None
        # Not covered even after a re-solve (e.g. solve throttled). Only
        # UNBOUNDED tasks are safe to hand out here: a replica-bounded
        # task's budget is accounted inside the solve, and _task_for_group
        # commits choices sticky via SET-NX — an uncovered-group fallback
        # grabbing a bounded task could exceed its replica bound
        # permanently. Bounded-only groups wait one beat instead.
        unbounded = [t for t in applicable if task_replicas(t) is None]
        if not unbounded:
            return None
        return max(unbounded, key=lambda t: t.created_at)

    def _warm_gate(self, seeded: int, rebuilt: bool = False) -> bool:
        """Single source of truth for warm eligibility + the periodic-cold
        counter (both the cached and the wire sparse paths go through it —
        drift between duplicated gates is how warm bugs hide)."""
        warm = (
            self.warm_start
            and seeded > 0
            and not rebuilt
            and self._warm_solves_since_cold < self.cold_every
        )
        if warm:
            self._warm_solves_since_cold += 1
        else:
            self._warm_solves_since_cold = 0
        return warm

    def _solve_slots_cached(self, prepared, tasks, bounded, slot_range) -> np.ndarray:
        """Phase 1 over the candidate cache's persistent structure: warm
        single-phase auction when seeds exist, eps-scaling ladder otherwise.
        Prices are stored back per-row so the NEXT solve re-bids only its
        delta."""
        p4s0 = np.full(prepared.cand_p.shape[0], -1, np.int32)
        seeded = self._seed_slots(
            p4s0, prepared.row_of_addr, tasks, bounded, slot_range
        )
        warm = self._warm_gate(seeded, rebuilt=prepared.rebuilt)
        cand_p = jnp.asarray(prepared.cand_p)
        cand_c = jnp.asarray(prepared.cand_c)
        # retirement carry: valid only while the slot layout (task ids ->
        # slot ranges) and the cached candidate structure are unchanged —
        # any rebuild or task churn invalidates the mask (slots renumber)
        slot_fp = (
            tuple(sorted((tasks[i].id,) + tuple(slot_range[i]) for i, _ in bounded)),
            int(p4s0.shape[0]),
        )
        retired0 = None
        if warm and self._warm_retired is not None and self._warm_retired_fp == slot_fp:
            carried = np.asarray(self._warm_retired)
            if prepared.dirty_slots is None:
                # unknown provenance (first prepare after a relayout the
                # slot_fp missed): drop the whole mask rather than carry
                # flags over changed candidates
                carried = None
            else:
                # the warm kernel's contract: rows whose candidates changed
                # must be cleared by the caller — otherwise a task stays
                # retired after a newly-feasible provider churns into its
                # list and sits unassigned until the next cold solve
                # (ADVICE r5). dirty_slots is the cache-side signal;
                # claim-masking (this solve's AND last solve's — a released
                # claim restores candidates) edits lists after the cache
                # compared, so those rows are dirty too.
                dirty = prepared.dirty_slots.copy()
                for claim_rows in (
                    self._claim_rows_now, self._claim_rows_prev
                ):
                    if claim_rows is not None:
                        if claim_rows.shape == dirty.shape:
                            dirty |= claim_rows
                        else:
                            carried = None
                if carried is not None and dirty.shape == carried.shape:
                    carried = carried & ~dirty
                else:
                    carried = None
            if carried is not None:
                retired0 = jnp.asarray(carried)
        self._claim_rows_prev = self._claim_rows_now
        stall_stats: dict = {}
        res, price, retired = self._sparse_solve(
            cand_p, cand_c, prepared.p_bucket, warm,
            jnp.asarray(prepared.price0), jnp.asarray(p4s0),
            stats_out=stall_stats, retired0=retired0,
        )
        self._cache.store_prices(np.asarray(price))
        self._warm_retired = np.asarray(retired)
        self._warm_retired_fp = slot_fp
        self._last_warm_used = warm
        self._last_warm_seeded = seeded
        self._last_stall = stall_stats
        return np.asarray(res.task_for_provider)[: prepared.num_rows]

    def _unbounded_best(self, ep, er) -> np.ndarray:
        if self.native_fallback:
            cost = self._native_cost(ep, er)
            best = cost.argmin(axis=1).astype(np.int32)
            feas = cost[np.arange(cost.shape[0]), best] < INFEASIBLE * 0.5
            return np.where(feas, best, -1).astype(np.int32)
        best, _feas = _solve_unbounded(ep, er, self.weights)
        return np.asarray(best)

    # ----- batch solve

    def refresh(self) -> None:
        """One batch solve; with PROTOCOL_TPU_PROFILE_DIR set, each solve
        is captured as an xprof trace (SURVEY §5's stated tracing plan:
        JAX profiler instead of the reference's log-line timing)."""
        profile_dir = os.environ.get("PROTOCOL_TPU_PROFILE_DIR", "")
        if profile_dir:
            # jax.profiler.trace is process-global and cannot nest: one
            # lock across ALL matcher instances (devnet runs several)
            with _PROFILE_LOCK, jax.profiler.trace(profile_dir):
                self._refresh()
            return
        self._refresh()

    def _refresh(self) -> None:
        t_start = time.perf_counter()
        # clear the dirty flag BEFORE reading state: a concurrent mark_dirty
        # landing mid-read must trigger another solve, not be erased
        self._dirty = False
        self._last_solve = self._time()
        nodes = [
            n for n in self.store.node_store.get_nodes() if n.status in SCHEDULABLE
        ]
        tasks = self.store.task_store.get_all_tasks()
        # Drop tasks with malformed plugin config (validated at creation via
        # validate_tpu_scheduler_config; this guards direct store writes).
        ok_tasks = []
        for t in tasks:
            try:
                task_replicas(t)
                task_requirements(t)
                task_anti_affinity(t)
                task_colocate(t)
            except Exception:
                continue
            ok_tasks.append(t)
        tasks = ok_tasks
        # newest-first priority, matching NewestTaskPlugin ordering:
        # normalize created_at to [0, 1] so the priority cost term dominates
        # ties in the same direction as the reference's sort.
        if tasks:
            created = np.asarray([t.created_at for t in tasks], np.float64)
            span = max(created.max() - created.min(), 1.0)
            prio = ((created - created.min()) / span).astype(np.float32)
        else:
            prio = np.zeros(0, np.float32)

        # ---- group phase (composed gang scheduling): groups are
        # pseudo-providers in a topology-masked cost solve; grouped nodes
        # leave the individual solve entirely
        if self._groups_plugin is not None:
            groups = self._groups_plugin.get_groups()
            try:
                self._group_assignment, self._group_covered = (
                    self._solve_groups(groups, tasks, prio)
                )
            except Exception:
                logging.getLogger(__name__).exception("group solve failed")
                self._group_assignment, self._group_covered = {}, set()
            grouped = {a for g in groups for a in g.nodes}
            nodes = [n for n in nodes if n.address not in grouped]

        # build the new solution locally and swap at the end so concurrent
        # readers never observe a half-built assignment
        assignment: dict[str, str] = {}
        covered = {n.address for n in nodes}
        if not nodes or not tasks:
            self._assignment_multi = {}
            self._assignment, self._covered = assignment, covered
            self._solve_seq += 1
            self.last_solve_stats = {
                "nodes": len(nodes),
                "tasks": len(tasks),
                "group_assignments": len(self._group_assignment),
                "seq": self._solve_seq,
            }
            return

        bounded: list[tuple[int, int]] = []  # (task idx, replicas)
        unbounded: list[int] = []
        aa: list[tuple[int, int, str]] = []  # (task idx, replicas, mode)
        colo: list[tuple[int, int]] = []  # (task idx, replicas), capacity-sharing
        for i, t in enumerate(tasks):
            if t.allowed_topologies() and self._groups_plugin is not None:
                # topology-restricted tasks are group-only when gang
                # scheduling is active: handing one to an individual node
                # would violate the gang contract. Without a groups plugin
                # (no gang semantics in this deployment) they stay
                # individually schedulable as before.
                continue
            r = task_replicas(t)
            if r is None:
                unbounded.append(i)
            elif task_colocate(t):
                colo.append((i, r))
            else:
                mode = task_anti_affinity(t)
                if mode:
                    aa.append((i, r, mode))
                else:
                    bounded.append((i, r))

        P = len(nodes)
        p_bucket = _pow2_bucket(P)

        truncated_slots = 0
        kernel_used = "none"
        warm_used = False
        warm_seeded = 0
        cache_stats: dict = {}

        # ---- replica-slot expansion for bounded tasks (cheap, host-side)
        slot_task: list[int] = []
        slot_range: dict[int, tuple[int, int]] = {}  # task idx -> (start, n)
        req_by_task: dict[int, ComputeRequirements] = {}
        if bounded:
            req_by_task = {i: task_requirements(tasks[i]) for i, _ in bounded}
            # the native degraded-mode engine solves dense on the host: it
            # keeps the old 4096-slot envelope regardless of the (much
            # larger) sparse-path default
            slot_cap = (
                min(self.max_replica_slots, 4096)
                if self.native_fallback
                else self.max_replica_slots
            )
            for i, r in bounded:
                take = min(min(r, P), slot_cap - len(slot_task))
                slot_range[i] = (len(slot_task), take)
                slot_task.extend([i] * take)
                if len(slot_task) >= slot_cap:
                    break
            # arithmetic, not loop iterations: demand can be ~1M slots
            truncated_slots = sum(min(r, P) for _, r in bounded) - len(slot_task)
            if truncated_slots:
                # never a silent cap: at 1M-scale demand, dropped replica
                # slots are a capacity decision the operator must see
                logging.getLogger(__name__).warning(
                    "replica demand exceeds max_replica_slots=%d: "
                    "%d slots dropped this solve",
                    self.max_replica_slots,
                    truncated_slots,
                )
        self._last_sharded = False  # set by _sparse_solve when it engages
        self._last_arena_stats = {}  # set by _bounded_t4p on the native path
        self._claim_rows_now = None  # set by the claim-masking block below
        s_bucket = _pow2_bucket(len(slot_task)) if slot_task else 0
        use_sparse = bool(slot_task) and (
            not self.native_fallback
            # the jax engine owns phase 1 through its arena (which IS
            # the sparse pipeline, warm): the stateless sparse_topk
            # rung would re-pay cold generation every solve
            and not self._jax_engine
            and p_bucket * s_bucket > self.dense_cell_budget
        )
        # The candidate cache owns the provider index space on the cached
        # path: rows are stable across solves (dead rows masked invalid), so
        # per-solve encoding is O(churn) and candidate structure persists.
        cached_path = (
            use_sparse and self.warm_start and self.use_candidate_cache
        )

        prepared = None
        if cached_path:
            if self._warm_solves_since_cold >= self.cold_every:
                # periodic full re-ground: fresh candidate selection AND
                # fresh prices (bounds both selection staleness from base
                # drift and the warm chain's monotone price ratchet)
                self._cache.invalidate()
            pitems = [
                ProviderItem(
                    addr=n.address,
                    specs=n.compute_specs,
                    location=n.location,
                    price=n.price or 0.0,
                    load=n.load or 0.0,
                )
                for n in nodes
            ]
            titems = [
                TaskItem(
                    task_id=tasks[i].id,
                    requirement=req_by_task[i],
                    take=slot_range[i][1],
                    prio=float(prio[i]),
                )
                for i, _ in bounded
                if i in slot_range and slot_range[i][1] > 0
            ]
            prepared = self._cache.prepare(pitems, titems)
            ep = prepared.ep
            idx_addrs = prepared.addr_of_row
            N = prepared.num_rows
            cache_stats = {
                "cache_rebuilt": prepared.rebuilt,
                "cache_delta_rows": prepared.delta_rows,
                "cache_delta_tasks": prepared.delta_tasks,
                "cache_uncovered_rows": prepared.uncovered_rows,
                "cache_stale_frac": round(prepared.stale_frac, 4),
            }
        else:
            specs = [n.compute_specs for n in nodes]
            locs = [n.location for n in nodes]
            ep = self.encoder.encode_providers(
                specs,
                locations=locs,
                prices=[n.price or 0.0 for n in nodes],
                loads=[n.load or 0.0 for n in nodes],
                pad_to=p_bucket,
            )
            idx_addrs = [n.address for n in nodes]
            N = P

        assigned = np.zeros(N, bool)

        # ---- phase 0: anti-affinity tasks -> bin-pack with exclusion
        # domains (ladder #5's anti-affinity term, live): replicas spread
        # across distinct providers/locations via ops/binpack; claimed
        # providers are then excluded from the auction and phase 2.
        aa_assigned = 0
        self._aa_truncated = 0
        claims: dict[int, int] = {}
        if aa:
            loc_by_addr = {n.address: n.location for n in nodes}
            claims = self._solve_anti_affinity(
                ep, N, aa, tasks, prio, idx_addrs, loc_by_addr
            )
            for row, i in claims.items():
                assignment[idx_addrs[row]] = tasks[i].id
                assigned[row] = True
            aa_assigned = len(claims)

        # ---- phase 0.5: colocation -> capacity bin-pack (ladder #5 live:
        # several replicas stack on one provider while its GPU/VRAM/cpu/
        # ram/storage capacity holds — see _solve_colocation)
        colo_slots = 0
        self._colo_truncated = 0
        self._colo_requested = 0
        assignment_multi: dict[str, list[str]] = {}
        placed: dict[int, list[int]] = {}
        if colo:
            placed = self._solve_colocation(
                ep, N, colo, tasks, prio, set(claims)
            )
            for row, tidxs in placed.items():
                addr = idx_addrs[row]
                assignment[addr] = tasks[tidxs[0]].id
                # several replicas of the SAME task stacking on one
                # provider reserve that many capacity slots, but execution
                # is one instance per distinct task per node (the worker
                # dedups by task id; reference semantics) — the wire list
                # carries distinct ids only
                assignment_multi[addr] = list(
                    dict.fromkeys(tasks[j].id for j in tidxs)
                )
                assigned[row] = True
                colo_slots += len(tidxs)
            if colo_slots < self._colo_requested:
                # never a silent cap: unplaced colocated replicas are a
                # capacity verdict the operator must see
                logging.getLogger(__name__).warning(
                    "colocation placed %d/%d replica slots (insufficient "
                    "fleet capacity for the rest)",
                    colo_slots, self._colo_requested,
                )

        claimed_rows = list(claims) + list(placed)
        if claimed_rows:
            claimed = np.zeros(int(np.asarray(ep.valid).shape[0]), bool)
            claimed[claimed_rows] = True
            # the auction must not re-assign a claimed provider: drop
            # them from the compatibility domain (ep.valid gates
            # compat_mask) and from any pre-assembled candidate lists
            import dataclasses as _dc

            ep = _dc.replace(
                ep, valid=jnp.asarray(np.asarray(ep.valid) & ~claimed)
            )
            if prepared is not None:
                cp = prepared.cand_p
                masked = (cp >= 0) & claimed[np.maximum(cp, 0)]
                prepared.cand_p = np.where(masked, -1, cp)
                self._claim_rows_now = masked.any(axis=1)

        # ---- phase 1: bounded tasks -> replica slots -> auction
        if slot_task:
            if cached_path:
                kernel_used = "sparse_topk"
                t4p = self._solve_slots_cached(
                    prepared, tasks, bounded, slot_range
                )
                warm_used = self._last_warm_used
                warm_seeded = self._last_warm_seeded
            elif use_sparse:
                kernel_used = "sparse_topk"
                er = self.encoder.encode_requirements(
                    [req_by_task[i] for i in slot_task],
                    priorities=[prio[i] for i in slot_task],
                    pad_to=s_bucket,
                )
                price0 = np.zeros(p_bucket, np.float32)
                p4s0 = np.full(s_bucket, -1, np.int32)
                addrs = idx_addrs
                if self.warm_start:
                    get_price = self._warm_price_by_addr.get
                    price0[:P] = np.fromiter(
                        (get_price(a, 0.0) for a in addrs), np.float32, count=P
                    )
                    warm_seeded = self._seed_slots(
                        p4s0, {a: i for i, a in enumerate(addrs)},
                        tasks, bounded, slot_range,
                    )
                warm_used = self._warm_gate(warm_seeded)
                t4p, price = self._bounded_t4p_sparse(
                    ep, er, price0, p4s0, warm=warm_used
                )
                t4p = t4p[:P]
                if self.warm_start:
                    self._warm_price_by_addr = dict(
                        zip(addrs, np.asarray(price[:P], np.float64).tolist())
                    )
            else:
                if self._jax_engine:
                    kernel_used = "jax_arena"
                elif not self.native_fallback:
                    kernel_used = "dense_auction"
                elif self.native_engine == "sinkhorn-mt":
                    kernel_used = "native_cpu_sinkhorn_mt"
                elif self.native_engine == "native-mt":
                    kernel_used = "native_cpu_mt"
                else:
                    kernel_used = "native_cpu"
                er = self.encoder.encode_requirements(
                    [req_by_task[i] for i in slot_task],
                    priorities=[prio[i] for i in slot_task],
                    pad_to=s_bucket,
                )
                t4p = self._bounded_t4p(ep, er)[:N]
            for p_idx, s_idx in enumerate(t4p):
                if s_idx >= 0 and s_idx < len(slot_task):
                    assignment[idx_addrs[p_idx]] = tasks[slot_task[s_idx]].id
                    assigned[p_idx] = True

        # ---- phase 2: remaining nodes -> cheapest compatible unbounded task
        if unbounded and not assigned.all():
            reqs = [task_requirements(tasks[i]) for i in unbounded]
            prios = [prio[i] for i in unbounded]
            t_bucket = _pow2_bucket(len(unbounded))
            er = self.encoder.encode_requirements(
                reqs, priorities=prios, pad_to=t_bucket
            )
            best = self._unbounded_best(ep, er)[:N]
            for p_idx in range(N):
                if not assigned[p_idx] and best[p_idx] >= 0 and best[p_idx] < len(unbounded):
                    assignment[idx_addrs[p_idx]] = tasks[unbounded[best[p_idx]]].id

        # store order matters for lock-free readers (tasks_for_node checks
        # _assignment_multi FIRST, then falls back to _assignment): writing
        # multi before the main map means a reader racing the swap serves
        # the previous solve wholesale — never a new-solve/old-multi mix
        # that would hand a no-longer-colocated node a stale task list
        self._assignment_multi = assignment_multi
        self._assignment, self._covered = assignment, covered
        self._solve_seq += 1
        self.last_solve_stats = {
            "nodes": P,
            "tasks": len(tasks),
            "bounded_tasks": len(bounded),
            "assigned": len(assignment),
            "colocated_slots": colo_slots,
            "colocated_unplaced": self._colo_requested - colo_slots,
            "truncated_colocate_slots": self._colo_truncated,
            "solve_ms": (time.perf_counter() - t_start) * 1e3,
            "truncated_replica_slots": truncated_slots,
            "kernel": kernel_used,  # dense_auction | sparse_topk | native_cpu
            # True when phase 1 ran the task-sharded mesh kernels (the
            # use_mesh path actually engaging, not merely requested)
            "mesh_sharded": self._last_sharded,
            "mesh_gen_sharded": self._last_gen_sharded,
            "warm": warm_used,
            "warm_seeded_slots": warm_seeded,
            # binding-phase stall circuit breaker (ops/sparse.py): True
            # means tail quality fell to greedy cleanup this solve
            "stall_exit": self._last_stall.get("stall_exit", False),
            "anti_affinity_assigned": aa_assigned,
            "truncated_aa_slots": self._aa_truncated,
            "group_assignments": len(self._group_assignment),
            "seq": self._solve_seq,  # monotone id for scrape-side dedup
            **cache_stats,
            # native-mt only: what the persistent arena reused vs recomputed
            **self._last_arena_stats,
        }
